"""Linear rectifier: max(x, threshold) (+ optional alpha offset).

Ref: src/main/scala/nodes/stats/LinearRectifier.scala [unverified].
"""

from __future__ import annotations

import jax.numpy as jnp

from keystone_tpu.workflow import Transformer


class LinearRectifier(Transformer):
    def __init__(self, max_val: float = 0.0, alpha: float = 0.0):
        self.max_val = max_val
        self.alpha = alpha

    def signature(self):
        return self.stable_signature(self.max_val, self.alpha)

    def apply_batch(self, X):
        return jnp.maximum(X - self.alpha, self.max_val)
