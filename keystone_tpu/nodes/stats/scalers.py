"""Mean/std standardization estimator.

Ref: src/main/scala/nodes/stats/StandardScaler.scala — fit computes column
mean (and optionally std); the model subtracts/divides [unverified].
"""

from __future__ import annotations

import jax.numpy as jnp

from keystone_tpu.workflow import Estimator, Transformer


class StandardScalerModel(Transformer):
    def __init__(self, mean, std=None):
        self.mean = jnp.asarray(mean)
        self.std = None if std is None else jnp.asarray(std)

    def apply_batch(self, X):
        out = X - self.mean
        if self.std is not None:
            out = out / self.std
        return out


class StandardScaler(Estimator):
    def __init__(self, normalize_std_dev: bool = True, eps: float = 1e-8):
        self.normalize_std_dev = normalize_std_dev
        self.eps = eps

    def fit(self, data) -> StandardScalerModel:
        X = jnp.asarray(data)
        mean = X.mean(axis=0)
        std = None
        if self.normalize_std_dev:
            std = jnp.maximum(X.std(axis=0, ddof=1), self.eps)
        return StandardScalerModel(mean, std)
