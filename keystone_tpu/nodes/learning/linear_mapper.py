"""Dense linear model fit by distributed least squares.

Ref: src/main/scala/nodes/learning/LinearMapper.scala —
`LinearMapEstimator(lambda)` solves ridge least squares on (features,
±1-indicator labels) through ml-matrix, producing `LinearMapper(x, bOpt,
featureScaler)`: scores = (X − μ) W + b [unverified].

TPU lowering: features/labels go row-sharded over the mesh (`RowMatrix`),
the solve is normal equations with `psum`-reduced grams (or TSQR for the
ill-conditioned case), and the fitted mapper is one MXU gemm.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from keystone_tpu.linalg import (
    RowMatrix,
    solve_least_squares_normal,
    solve_least_squares_tsqr,
)
from keystone_tpu.workflow import LabelEstimator, Transformer


class LinearMapper(Transformer):
    def __init__(self, W, b: Optional[jax.Array] = None):
        self.W = jnp.asarray(W)
        self.b = None if b is None else jnp.asarray(b)

    def apply_batch(self, X):
        out = X @ self.W
        if self.b is not None:
            out = out + self.b
        return out


class LinearMapEstimator(LabelEstimator):
    """Ridge least squares with an intercept fit by centering.

    The intercept comes from centering both sides (the reference pairs the
    solve with a feature-mean scaler): W solves the centered ridge problem,
    b = ȳ − x̄ᵀW.
    """

    def __init__(self, lam: float = 0.0, method: str = "normal"):
        if method not in ("normal", "tsqr"):
            raise ValueError("method must be 'normal' or 'tsqr'")
        self.lam = lam
        self.method = method

    def fit(self, data, labels) -> LinearMapper:
        from keystone_tpu.linalg.row_matrix import storage_dtype

        X = jnp.asarray(data)
        Y = jnp.asarray(labels)
        x_mean = X.mean(axis=0)
        y_mean = Y.mean(axis=0)
        if self.method == "tsqr":
            # QR is storage-dtype-sensitive; TSQR keeps full width.
            A = RowMatrix.from_array(X - x_mean)
            B = RowMatrix.from_array(Y - y_mean)
            W = solve_least_squares_tsqr(A, B, self.lam)
        else:
            # Normal equations: A may store bf16 (gram accumulates f32).
            A = RowMatrix.from_array(X - x_mean, dtype=storage_dtype())
            B = RowMatrix.from_array(Y - y_mean)
            W = solve_least_squares_normal(A, B, self.lam)
        b = y_mean - x_mean @ W
        return LinearMapper(W, b)
