"""Dense linear model fit by distributed least squares.

Ref: src/main/scala/nodes/learning/LinearMapper.scala —
`LinearMapEstimator(lambda)` solves ridge least squares on (features,
±1-indicator labels) through ml-matrix, producing `LinearMapper(x, bOpt,
featureScaler)`: scores = (X − μ) W + b [unverified].

TPU lowering: features/labels go row-sharded over the mesh (`RowMatrix`),
the solve is normal equations with `psum`-reduced grams (or TSQR for the
ill-conditioned case), and the fitted mapper is one MXU gemm.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from keystone_tpu.linalg import (
    RowMatrix,
    solve_least_squares_normal,
    solve_least_squares_tsqr,
)
from keystone_tpu.workflow import LabelEstimator, Transformer


class LinearMapper(Transformer):
    def __init__(self, W, b: Optional[jax.Array] = None):
        self.W = jnp.asarray(W)
        self.b = None if b is None else jnp.asarray(b)

    def apply_batch(self, X):
        out = X @ self.W
        if self.b is not None:
            out = out + self.b
        return out


class LinearMapEstimator(LabelEstimator):
    """Ridge least squares with an intercept fit by centering.

    The intercept comes from centering both sides (the reference pairs the
    solve with a feature-mean scaler): W solves the centered ridge problem,
    b = ȳ − x̄ᵀW.
    """

    def __init__(self, lam: float = 0.0, method: str = "normal"):
        if method not in ("normal", "tsqr"):
            raise ValueError("method must be 'normal' or 'tsqr'")
        self.lam = lam
        self.method = method

    def partial_fit(self, data, labels, state=None, decay=None,
                    window=None, chunk_rows=None):
        """Fold one labeled batch into retained normal-equation
        accumulators (``workflow.online.OnlineState``) — create the
        state on first call, mutate-and-return it after. The fold is
        grouping-invariant: K calls are bit-identical to one call over
        the concatenation. ``solve_online`` re-solves cheaply."""
        from keystone_tpu.workflow.online import partial_fit_step

        return partial_fit_step(state, data, labels, decay=decay,
                                window=window, chunk_rows=chunk_rows)

    def solve_online(self, state) -> LinearMapper:
        """Re-solve the retained accumulators through the existing
        Cholesky path: the intercept rides the retained weighted means
        (exact rank-one centering correction), matching the batch fit's
        b = ȳ − x̄ᵀW semantics."""
        W, b = state.solve(self.lam)
        return LinearMapper(W, b)

    def fit(self, data, labels) -> LinearMapper:
        from keystone_tpu.linalg.row_matrix import storage_dtype

        X = jnp.asarray(data)
        Y = jnp.asarray(labels)
        # Placement-invariant centering: the means ride the same re-shard
        # + per-shard-sum + psum path as the grams (RowMatrix.col_sums),
        # so the fit is bit-identical whether the features arrived
        # sharded, replicated, or on one device — the data-parallel walk
        # can never perturb a solve. Centering derives on-device from the
        # ONE placed copy (RowMatrix.centered: subtract, re-zero pad
        # rows, cast) — no second host-to-device transfer of X.
        Ax = RowMatrix.from_array(X, dtype=X.dtype)
        Ay = RowMatrix.from_array(Y, dtype=Y.dtype)
        x_mean = Ax.col_sums() / Ax.n
        y_mean = Ay.col_sums() / Ay.n
        from keystone_tpu.config import config

        full = jnp.dtype(config.default_dtype)
        if self.method == "tsqr":
            # QR is storage-dtype-sensitive; TSQR keeps full width.
            W = solve_least_squares_tsqr(
                Ax.centered(x_mean, dtype=full),
                Ay.centered(y_mean, dtype=full),
                self.lam,
            )
        else:
            # Normal equations: A may store bf16 (gram accumulates f32).
            W = solve_least_squares_normal(
                Ax.centered(x_mean, dtype=storage_dtype()),
                Ay.centered(y_mean, dtype=full),
                self.lam,
            )
        b = y_mean - x_mean @ W
        return LinearMapper(W, b)
