"""K-Means++ clustering.

Ref: src/main/scala/nodes/learning/KMeansPlusPlus.scala —
`KMeansPlusPlusEstimator(k, maxIters)` with kmeans++ seeding; `KMeansModel`
transforms a vector to the one-hot encoding of its nearest center (the
feature-encoding use in pipelines) [unverified].

TPU lowering: Lloyd iterations are one fused computation per sweep —
pairwise distances (MXU gemm), argmin, segment-sum recentering — scanned
with lax.fori_loop so the whole fit is a single XLA program.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.config import config
from keystone_tpu.workflow import Estimator, Transformer


from keystone_tpu.nodes.learning.kernels import pairwise_sq_dists as _sq_dists


class KMeansModel(Transformer):
    def __init__(self, centers: jax.Array):
        self.centers = jnp.asarray(centers)

    def apply_batch(self, X):
        """One-hot nearest-center encoding (the reference's transform)."""
        assign = jnp.argmin(_sq_dists(X, self.centers), axis=1)
        return jax.nn.one_hot(
            assign, self.centers.shape[0], dtype=config.default_dtype
        )

    def predict(self, X):
        return jnp.argmin(_sq_dists(jnp.asarray(X), self.centers), axis=1)


@partial(jax.jit, static_argnames=("k", "max_iters"))
def _fit_kmeans(X, key, k: int, max_iters: int):
    n = X.shape[0]

    # -- kmeans++ seeding (distance-weighted sampling) --
    def seed_step(i, carry):
        centers, d2, key = carry
        key, sub = jax.random.split(key)
        probs = d2 / jnp.maximum(d2.sum(), 1e-12)
        idx = jax.random.choice(sub, n, p=probs)
        centers = centers.at[i].set(X[idx])
        new_d2 = jnp.sum((X - X[idx]) ** 2, axis=1)
        return centers, jnp.minimum(d2, new_d2), key

    key, sub = jax.random.split(key)
    first = X[jax.random.randint(sub, (), 0, n)]
    centers0 = jnp.zeros((k, X.shape[1]), X.dtype).at[0].set(first)
    d2_0 = jnp.sum((X - first) ** 2, axis=1)
    centers, _, key = jax.lax.fori_loop(
        1, k, seed_step, (centers0, d2_0, key)
    )

    # -- Lloyd iterations --
    def lloyd(_i, centers):
        assign = jnp.argmin(_sq_dists(X, centers), axis=1)
        onehot = jax.nn.one_hot(assign, k, dtype=X.dtype)  # (n, k)
        counts = onehot.sum(axis=0)  # (k,)
        sums = onehot.T @ X  # (k, d) — MXU
        new = sums / jnp.maximum(counts, 1.0)[:, None]
        # Keep old center for empty clusters.
        return jnp.where((counts > 0)[:, None], new, centers)

    return jax.lax.fori_loop(0, max_iters, lloyd, centers)


class KMeansPlusPlusEstimator(Estimator):
    def __init__(self, k: int, max_iters: int = 20, seed: int = 0):
        self.k = k
        self.max_iters = max_iters
        self.seed = seed

    def fit(self, data) -> KMeansModel:
        X = jnp.asarray(data, dtype=config.default_dtype)
        centers = _fit_kmeans(
            X, jax.random.PRNGKey(self.seed), self.k, self.max_iters
        )
        return KMeansModel(centers)
