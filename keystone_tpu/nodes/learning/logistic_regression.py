"""Multinomial logistic regression trained with LBFGS.

Ref: src/main/scala/nodes/learning/LogisticRegressionEstimator.scala —
wraps MLlib `LogisticRegressionWithLBFGS` (SURVEY.md §2.4) [unverified].
Re-implemented natively: optax LBFGS minimizing softmax cross-entropy +
L2, the whole optimization loop compiled as one XLA while-loop.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.config import config
from keystone_tpu.workflow import LabelEstimator, Transformer


class LogisticRegressionModel(Transformer):
    def __init__(self, W, b):
        self.W = jnp.asarray(W)
        self.b = jnp.asarray(b)

    def apply_batch(self, X):
        """Class scores (logits); compose MaxClassifier for labels."""
        from keystone_tpu.utils.sparse import SparseBatch

        if isinstance(X, SparseBatch):
            return X.matmul(np.asarray(self.W)) + np.asarray(self.b)
        return X @ self.W + self.b


@partial(jax.jit, static_argnames=("num_classes", "max_iters"))
def _fit_lbfgs(X, y, num_classes: int, reg: float, max_iters: int):
    import optax  # deferred: only this estimator needs optax

    n, d = X.shape
    onehot = jax.nn.one_hot(y, num_classes, dtype=X.dtype)

    def loss_fn(params):
        W, b = params
        logits = X @ W + b
        ce = -jnp.mean(
            jnp.sum(onehot * jax.nn.log_softmax(logits, axis=-1), axis=-1)
        )
        return ce + 0.5 * reg * jnp.sum(W * W)

    params = (
        jnp.zeros((d, num_classes), X.dtype),
        jnp.zeros((num_classes,), X.dtype),
    )
    opt = optax.lbfgs()
    state = opt.init(params)
    value_and_grad = optax.value_and_grad_from_state(loss_fn)

    def step(carry, _):
        params, state = carry
        value, grad = value_and_grad(params, state=state)
        updates, state = opt.update(
            grad, state, params, value=value, grad=grad, value_fn=loss_fn
        )
        params = optax.apply_updates(params, updates)
        return (params, state), value

    (params, _state), _losses = jax.lax.scan(
        step, (params, state), None, length=max_iters
    )
    return params


class LogisticRegressionEstimator(LabelEstimator):
    def __init__(
        self,
        num_classes: int,
        reg: float = 1e-4,
        max_iters: int = 100,
    ):
        self.num_classes = num_classes
        self.reg = reg
        self.max_iters = max_iters

    def fit(self, data, labels) -> LogisticRegressionModel:
        from keystone_tpu.utils.sparse import SparseBatch

        if isinstance(data, SparseBatch):
            # Device-sparse fit: the LBFGS loop re-reads X every iteration,
            # so X rides along as a BCOO — `X @ W` inside the jitted loss
            # stays sparse and an (n, vocab) dense array never exists.
            X = data.to_bcoo(dtype=config.default_dtype)
        else:
            X = jnp.asarray(data, dtype=config.default_dtype)
        y = jnp.asarray(labels).astype(jnp.int32).ravel()
        W, b = _fit_lbfgs(X, y, self.num_classes, self.reg, self.max_iters)
        return LogisticRegressionModel(W, b)
