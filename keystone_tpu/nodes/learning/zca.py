"""ZCA whitening (the RandomPatchCifar preprocessing).

Ref: src/main/scala/nodes/images/ZCAWhitener.scala —
`ZCAWhitenerEstimator(eps)` fits on the patch matrix via SVD; the whitener
maps x → (x − μ) V (S²/n + εI)^(−1/2) Vᵀ (SURVEY.md §2.4) [unverified].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from keystone_tpu.workflow import Estimator, Transformer


class ZCAWhitener(Transformer):
    def __init__(self, whitener: jax.Array, mean: jax.Array):
        self.whitener = jnp.asarray(whitener)  # (d, d)
        self.mean = jnp.asarray(mean)

    def apply_batch(self, X):
        return (X - self.mean) @ self.whitener


class ZCAWhitenerEstimator(Estimator):
    def __init__(self, eps: float = 0.1):
        self.eps = eps

    def fit(self, data) -> ZCAWhitener:
        X = jnp.asarray(data)
        n = X.shape[0]
        mean = X.mean(axis=0)
        Xc = X - mean
        # Eigendecomposition of the covariance (symmetric, stable on TPU).
        cov = (Xc.T @ Xc) / n + 0.0
        evals, evecs = jnp.linalg.eigh(cov)
        scale = 1.0 / jnp.sqrt(jnp.maximum(evals, 0.0) + self.eps)
        whitener = (evecs * scale) @ evecs.T
        return ZCAWhitener(whitener, mean)
