"""Linear discriminant analysis (projection learning).

Ref: src/main/scala/nodes/learning/LinearDiscriminantAnalysis.scala —
solves the generalized eigenproblem on between-/within-class scatter and
projects onto the top discriminant directions [unverified].
"""

from __future__ import annotations

import jax.numpy as jnp

from keystone_tpu.config import config
from keystone_tpu.nodes.learning.pca import PCATransformer
from keystone_tpu.workflow import LabelEstimator


class LinearDiscriminantAnalysis(LabelEstimator):
    def __init__(self, dims: int, eps: float = 1e-6):
        self.dims = dims
        self.eps = eps

    def fit(self, data, labels) -> PCATransformer:
        X = jnp.asarray(data, dtype=config.default_dtype)
        y = jnp.asarray(labels).astype(jnp.int32).ravel()
        classes = jnp.unique(y)  # host-side: label set is data-dependent
        mean = X.mean(axis=0)
        d = X.shape[1]
        Sw = jnp.zeros((d, d), X.dtype)
        Sb = jnp.zeros((d, d), X.dtype)
        for c in classes:
            mask = (y == c)[:, None].astype(X.dtype)
            nc = mask.sum()
            mu_c = (X * mask).sum(axis=0) / jnp.maximum(nc, 1.0)
            Xc = (X - mu_c) * mask
            Sw = Sw + Xc.T @ Xc
            diff = (mu_c - mean)[:, None]
            Sb = Sb + nc * (diff @ diff.T)
        # Solve Sw⁻¹ Sb via symmetric whitening for stability.
        evals_w, evecs_w = jnp.linalg.eigh(
            Sw + self.eps * jnp.eye(d, dtype=X.dtype)
        )
        inv_sqrt = (evecs_w / jnp.sqrt(evals_w)) @ evecs_w.T
        M = inv_sqrt @ Sb @ inv_sqrt
        _evals, evecs = jnp.linalg.eigh(M)
        # eigh sorts ascending: take the top `dims`, best first.
        top = evecs[:, ::-1][:, : self.dims]
        return PCATransformer(inv_sqrt @ top, mean)
