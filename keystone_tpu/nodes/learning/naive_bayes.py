"""Multinomial naive Bayes.

Ref: src/main/scala/nodes/learning/NaiveBayesEstimator.scala — wraps Spark
MLlib `NaiveBayes` (multinomial, additive smoothing); the Newsgroups
classifier (SURVEY.md §2.4, §2.11) [unverified]. Re-implemented natively
(SURVEY.md §7 non-goals: MLlib internals) — fit is two reductions; the
model emits log-posterior scores, so MaxClassifier composes downstream.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.config import config
from keystone_tpu.utils.sparse import SparseBatch
from keystone_tpu.workflow import LabelEstimator, Transformer


class NaiveBayesModel(Transformer):
    def __init__(self, log_prior, log_likelihood):
        self.log_prior = jnp.asarray(log_prior)  # (k,)
        self.log_likelihood = jnp.asarray(log_likelihood)  # (k, d)

    def apply_batch(self, X):
        if isinstance(X, SparseBatch):
            # Host path: block-gemm accumulation, never (n, vocab) dense.
            return X.matmul(np.asarray(self.log_likelihood).T) + np.asarray(
                self.log_prior
            )
        return X @ self.log_likelihood.T + self.log_prior


class NaiveBayesEstimator(LabelEstimator):
    """fit(term-frequency features, int labels) with Laplace smoothing.

    Accepts dense batches or ``SparseBatch`` (vocab ≫ 10k): the per-class
    feature-count reduction is one grouped bincount over the CSR entries —
    the sparse analog of the onehotᵀ @ X gemm.
    """

    def __init__(self, num_classes: int, smoothing: float = 1.0):
        self.num_classes = num_classes
        self.smoothing = smoothing

    def fit(self, data, labels) -> NaiveBayesModel:
        k = self.num_classes
        y_np = np.asarray(labels).astype(np.int64).ravel()
        if y_np.size and (y_np.min() < 0 or y_np.max() >= k):
            raise ValueError(
                f"labels must lie in [0, {k}); got range "
                f"[{y_np.min()}, {y_np.max()}]"
            )
        if isinstance(data, SparseBatch):
            class_counts = jnp.asarray(
                np.bincount(y_np, minlength=k).astype(np.float32)
            )
            feature_counts = jnp.asarray(data.grouped_column_sums(y_np, k))
        else:
            X = jnp.asarray(data, dtype=config.default_dtype)
            y = jnp.asarray(y_np).astype(jnp.int32)
            onehot = jax.nn.one_hot(y, k, dtype=X.dtype)  # (n, k)
            class_counts = onehot.sum(axis=0)  # (k,)
            feature_counts = onehot.T @ X  # (k, d)
        log_prior = jnp.log(class_counts) - jnp.log(class_counts.sum())
        smoothed = feature_counts + self.smoothing
        log_likelihood = jnp.log(smoothed) - jnp.log(
            smoothed.sum(axis=1, keepdims=True)
        )
        return NaiveBayesModel(log_prior, log_likelihood)
