"""Single-device dense least squares for small d.

Ref: src/main/scala/nodes/learning/LocalLeastSquaresEstimator.scala —
collect to the driver and solve directly [unverified]. Here "local" means
one un-sharded XLA computation (still on the accelerator); it is the
low-(n, d) corner of the LeastSquaresEstimator cost model.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from keystone_tpu.nodes.learning.linear_mapper import LinearMapper
from keystone_tpu.workflow import LabelEstimator


@jax.jit
def _solve(X, Y, lam):
    x_mean = X.mean(axis=0)
    y_mean = Y.mean(axis=0)
    Xc = X - x_mean
    Yc = Y - y_mean
    d = X.shape[1]
    G = Xc.T @ Xc + lam * jnp.eye(d, dtype=X.dtype)
    W = jnp.linalg.solve(G, Xc.T @ Yc)
    return W, y_mean - x_mean @ W


class LocalLeastSquaresEstimator(LabelEstimator):
    def __init__(self, lam: float = 0.0):
        self.lam = lam

    def fit(self, data, labels) -> LinearMapper:
        X = jnp.asarray(data)
        Y = jnp.asarray(labels)
        W, b = _solve(X, Y, jnp.asarray(self.lam, dtype=X.dtype))
        return LinearMapper(W, b)
