"""Kernel ridge regression via distributed matrix-free conjugate gradient.

Ref: src/main/scala/nodes/learning/KernelRidgeRegression.scala +
KernelBlockLinearMapper — blocked kernel-matrix generation and a block
solver over Spark (SURVEY.md §2.4) [unverified].

TPU-first design: instead of staging kernel blocks through an RDD-style
cache, the regularized system (K + λI)α = Y is solved by conjugate
gradient where each matvec computes its kernel rows on the fly inside a
shard_map — every chip holds a row shard of the training data, builds its
(n_local, n) kernel block on the MXU, multiplies, and the CG scalars reduce
with psum. K is never materialized; HBM holds only data + one block per
step. The whole CG loop is one XLA while_loop.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from keystone_tpu.utils.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from keystone_tpu.config import config
from keystone_tpu.linalg.row_matrix import RowMatrix
from keystone_tpu.nodes.learning.kernels import GaussianKernelGenerator, KernelGenerator
from keystone_tpu.workflow import LabelEstimator, Transformer


class KernelBlockLinearMapper(Transformer):
    """scores(x) = k(x, X_train) @ α, computed in training-row blocks so the
    test-kernel block never exceeds (batch, block) in memory."""

    def __init__(self, kernel: KernelGenerator, X_train, alpha, block_size: int = 4096):
        self.kernel = kernel
        self.X_train = jnp.asarray(X_train)
        self.alpha = jnp.asarray(alpha)
        self.block_size = block_size

    def apply_batch(self, X):
        n = self.X_train.shape[0]
        out = None
        for s in range(0, n, self.block_size):
            e = min(s + self.block_size, n)
            kb = self.kernel.block(X, self.X_train[s:e])
            contrib = kb @ self.alpha[s:e]
            out = contrib if out is None else out + contrib
        return out


def _kernel_matvec(mesh: Mesh, axis: str, gamma: float):
    """Row-sharded (K + λI) v with on-the-fly kernel rows and padded
    rows/cols masked out of K — the ONE operator both CG variants iterate
    on (a drift between them would silently solve different systems)."""

    from keystone_tpu.nodes.learning.kernels import pairwise_sq_dists

    def matvec(x_sharded, x_full, mask, v, lam):
        def local(xl, ml, v):
            kl = jnp.exp(-gamma * pairwise_sq_dists(xl, x_full))
            kl = kl * mask[None, :] * ml[:, None]
            return kl @ v

        out = shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P()),
            out_specs=P(axis),
            check_vma=False,
        )(x_sharded, mask, v)
        return out + lam * v

    return matvec


@lru_cache(maxsize=None)
def _cg_fn(mesh: Mesh, axis: str, gamma: float, max_iters: int, tol: float):
    """CG solve of (K_gauss + λI)α = Y with on-the-fly kernel rows."""

    matvec = _kernel_matvec(mesh, axis, gamma)

    @jax.jit
    def solve(x_sharded, x_full, mask, Y, lam):
        b = Y
        x0 = jnp.zeros_like(b)
        r0 = b  # since x0 = 0
        p0 = r0
        rs0 = jnp.sum(r0 * r0)

        def cond(carry):
            _x, _r, _p, rs, i = carry
            return (rs > tol * tol) & (i < max_iters)

        def body(carry):
            x, r, p, rs, i = carry
            Ap = matvec(x_sharded, x_full, mask, p, lam)
            alpha = rs / jnp.maximum(jnp.sum(p * Ap), 1e-30)
            x = x + alpha * p
            r = r - alpha * Ap
            rs_new = jnp.sum(r * r)
            p = r + (rs_new / jnp.maximum(rs, 1e-30)) * p
            return x, r, p, rs_new, i + 1

        x, _r, _p, rs, iters = lax.while_loop(
            cond, body, (x0, r0, p0, rs0, jnp.int32(0))
        )
        return x, rs, iters

    return solve


@lru_cache(maxsize=None)
def _pcg_fn(mesh: Mesh, axis: str, gamma: float, max_iters: int, tol: float):
    """Nyström-preconditioned CG (the Falkon-family idea, PAPERS.md):
    landmarks L give the rank-m surrogate K̂ = C W⁻¹ Cᵀ with C = k(X, L),
    W = k(L, L); Woodbury turns (K̂ + λI)⁻¹ into
        (1/λ)·(I − C (λW + CᵀC)⁻¹ Cᵀ),
    two (n, m) MXU gemms + one replicated (m, m) Cholesky solve per
    application. RBF spectra decay fast, so M⁻¹(K + λI) clusters near 1 and
    CG converges in a fraction of the iterations — same matvec, same
    stopping rule, strictly fewer steps."""

    from jax.scipy.linalg import cho_factor, cho_solve

    from keystone_tpu.nodes.learning.kernels import pairwise_sq_dists

    matvec = _kernel_matvec(mesh, axis, gamma)

    @jax.jit
    def solve(x_sharded, x_full, mask, Y, lam, L, W):
        from jax.scipy.linalg import solve_triangular

        m = W.shape[0]
        # Whitened landmark block B = C L⁻ᵀ with W = L Lᵀ: the Woodbury
        # inner matrix becomes λI + BᵀB, whose conditioning is floored by λ
        # exactly — no scale-dependent jitter games (CᵀC alone can be
        # numerically rank-deficient for wide kernels and NaN the f32
        # Cholesky). Over-regularizing only weakens the preconditioner,
        # never the solution (CG iterates on the exact operator).
        Lw = jnp.linalg.cholesky(W + 1e-5 * jnp.eye(m, dtype=W.dtype))

        def b_local(xl, ml):
            cl = jnp.exp(-gamma * pairwise_sq_dists(xl, L)) * ml[:, None]
            return solve_triangular(Lw, cl.T, lower=True).T

        B = shard_map(
            b_local,
            mesh=mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=P(axis),
            check_vma=False,
        )(x_sharded, mask)

        def btb_local(bl):
            return lax.psum(bl.T @ bl, axis)

        BtB = shard_map(
            btb_local, mesh=mesh, in_specs=P(axis), out_specs=P(),
            check_vma=False,
        )(B)
        trace_scale = jnp.trace(BtB) / m
        G = BtB + (lam + 1e-6 * trace_scale) * jnp.eye(m, dtype=W.dtype)
        # NOTE: tried the BCD-style explicit G⁻¹ here (one-time inverse,
        # gemm per iteration) — it NaNs: the whitened Nyström G's top
        # eigenvalue is ~||B||² with only a λ floor below, cond can exceed
        # 1/eps_f32, and an explicit f32 inverse breaks PCG symmetry until
        # CG diverges. The two-pass cho_solve is the numerically safe form;
        # PCG's whole point is few iterations, so the per-iteration trsm
        # cost stays bounded.
        cholG = cho_factor(G)

        def btr(r):
            def local(bl, rl):
                return lax.psum(bl.T @ rl, axis)

            return shard_map(
                local, mesh=mesh, in_specs=(P(axis), P(axis)), out_specs=P(),
                check_vma=False,
            )(B, r)

        def bmul(t):
            def local(bl, t):
                return bl @ t

            return shard_map(
                local, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(axis),
                check_vma=False,
            )(B, t)

        def minv(r):
            return (r - bmul(cho_solve(cholG, btr(r)))) / lam

        b = Y
        x0 = jnp.zeros_like(b)
        r0 = b
        z0 = minv(r0)
        p0 = z0
        rz0 = jnp.sum(r0 * z0)
        rs0 = jnp.sum(r0 * r0)

        def cond(carry):
            _x, _r, _z, _p, _rz, rs, i = carry
            return (rs > tol * tol) & (i < max_iters)

        def body(carry):
            x, r, z, p, rz, rs, i = carry
            Ap = matvec(x_sharded, x_full, mask, p, lam)
            alpha = rz / jnp.maximum(jnp.sum(p * Ap), 1e-30)
            x = x + alpha * p
            r = r - alpha * Ap
            z = minv(r)
            rz_new = jnp.sum(r * z)
            p = z + (rz_new / jnp.maximum(rz, 1e-30)) * p
            return x, r, z, p, rz_new, jnp.sum(r * r), i + 1

        x, _r, _z, _p, _rz, rs, iters = lax.while_loop(
            cond, body, (x0, r0, z0, p0, rz0, rs0, jnp.int32(0))
        )
        return x, rs, iters

    return solve


class KernelRidgeRegression(LabelEstimator):
    """Gaussian-kernel ridge regression (other kernels via the un-sharded
    fallback path of KernelBlockLinearMapper)."""

    # Fit-time diagnostic, not identity (see workflow._estimator_signature).
    _signature_exclude = ("last_cg_iters",)

    def __init__(
        self,
        kernel: KernelGenerator | None = None,
        lam: float = 1e-3,
        gamma: float | None = None,
        max_iters: int = 200,
        tol: float = 1e-5,
        predict_block_size: int = 4096,
        precond_landmarks: int | None = None,
        seed: int = 0,
    ):
        if kernel is not None and gamma is not None:
            raise ValueError("pass either `kernel` or `gamma`, not both")
        if kernel is None:
            kernel = GaussianKernelGenerator(gamma if gamma is not None else 1.0)
        self.kernel = kernel
        self.lam = lam
        self.max_iters = max_iters
        self.tol = tol
        self.predict_block_size = predict_block_size
        # Nyström preconditioning: number of landmark rows (None = plain
        # CG). ~256-1024 typically cuts RBF iteration counts several-fold.
        self.precond_landmarks = precond_landmarks
        self.seed = seed
        self.last_cg_iters: int | None = None

    def fit(self, data, labels) -> KernelBlockLinearMapper:
        X = jnp.asarray(data, dtype=config.default_dtype)
        Y = jnp.asarray(labels, dtype=config.default_dtype)
        if Y.ndim == 1:
            Y = Y[:, None]
        if not isinstance(self.kernel, GaussianKernelGenerator):
            return self._fit_dense(X, Y)
        A = RowMatrix.from_array(X)
        n_pad = A.padded_rows
        mask = jnp.zeros((n_pad,), X.dtype).at[: A.n].set(1.0)
        Y_pad = jnp.pad(Y, ((0, n_pad - Y.shape[0]), (0, 0)))
        # Replicate the kernel-column data ONCE before the CG loop; a sharded
        # x_full closed over inside matvec would re-all-gather every iteration.
        x_full = jax.device_put(
            A.data, NamedSharding(A.mesh, P())
        )
        if self.precond_landmarks and self.lam <= 0.0:
            raise ValueError(
                "precond_landmarks requires lam > 0: the Woodbury "
                "preconditioner divides by lam (plain CG handles lam=0)"
            )
        if self.precond_landmarks:
            m = min(int(self.precond_landmarks), A.n)
            rng = np.random.default_rng(self.seed)
            idx = rng.choice(A.n, size=m, replace=False)
            # On-device gather: only the m landmark rows move, never a full
            # n×d device→host round trip.
            L = jax.device_put(
                X[jnp.asarray(np.sort(idx))], NamedSharding(A.mesh, P())
            )
            W = self.kernel.block(L, L)
            solve_p = _pcg_fn(
                A.mesh,
                config.data_axis,
                float(self.kernel.gamma),
                self.max_iters,
                float(self.tol),
            )
            alpha, _rs, iters = solve_p(
                A.data, x_full, mask, Y_pad,
                jnp.asarray(self.lam, X.dtype), L, W,
            )
        else:
            solve = _cg_fn(
                A.mesh,
                config.data_axis,
                float(self.kernel.gamma),
                self.max_iters,
                float(self.tol),
            )
            alpha, _rs, iters = solve(
                A.data, x_full, mask, Y_pad, jnp.asarray(self.lam, X.dtype)
            )
        self.last_cg_iters = int(iters)
        return KernelBlockLinearMapper(
            self.kernel, X, alpha[: A.n], self.predict_block_size
        )

    def _fit_dense(self, X, Y) -> KernelBlockLinearMapper:
        """Un-sharded fallback for non-Gaussian kernels: materialize K once
        and solve directly (fine at the sample sizes such kernels see)."""
        n = X.shape[0]
        K = self.kernel.block(X, X)
        alpha = jnp.linalg.solve(
            K + self.lam * jnp.eye(n, dtype=X.dtype), Y
        )
        self.last_cg_iters = 0
        return KernelBlockLinearMapper(
            self.kernel, X, alpha, self.predict_block_size
        )
