"""Block least squares — the workhorse solver for high-dimensional features.

Ref: src/main/scala/nodes/learning/BlockLinearMapper.scala —
`BlockLeastSquaresEstimator(blockSize, numIter, lambda)` runs ml-matrix
BlockCoordinateDescent over feature blocks and returns `BlockLinearMapper`
(per-block weights applied block-by-block); the CIFAR/TIMIT workhorse.
`BlockWeightedLeastSquaresEstimator(..., mixtureWeight)` is the
class-rebalanced ImageNet variant (SURVEY.md §2.4, §3.2) [unverified].

TPU lowering: see keystone_tpu/linalg/bcd.py. The intercept is fit by
centering features and labels (b = ȳ − x̄ᵀW), matching the reference's
mean-scaler pairing.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.config import config
from keystone_tpu.linalg import (
    RowMatrix,
    block_coordinate_descent,
    block_coordinate_descent_streamed,
)
from keystone_tpu.workflow import LabelEstimator, Transformer


class BlockLinearMapper(Transformer):
    """Applies W block-by-block: scores = Σ_b X_b W_b + b.

    Keeping per-block weights (instead of one dense (d, k) matrix) is what
    lets a 256k-dim model stream through memory; XLA fuses the per-block
    gemm+accumulate chain.
    """

    def __init__(
        self,
        W_blocks: Sequence[jax.Array],
        blocks: Sequence[Tuple[int, int]],
        b: Optional[jax.Array] = None,
    ):
        self.W_blocks = [jnp.asarray(w) for w in W_blocks]
        self.blocks = list(blocks)
        self.b = None if b is None else jnp.asarray(b)

    def apply_batch(self, X):
        from keystone_tpu.utils.sparse import SparseBatch

        out = None
        if isinstance(X, SparseBatch):
            # matmul densifies per column block internally — same streaming
            # shape as the dense loop below, one implementation.
            out = X.matmul(np.asarray(self.W))
            if self.b is not None:
                out = out + np.asarray(self.b)
            return out
        for (s, e), w in zip(self.blocks, self.W_blocks):
            contrib = X[..., s:e] @ w
            out = contrib if out is None else out + contrib
        if self.b is not None:
            out = out + self.b
        return out

    @property
    def W(self) -> jax.Array:
        return jnp.concatenate(self.W_blocks, axis=0)


def resolve_block_size(block_size, d: int) -> int:
    """Resolve ``block_size="auto"`` to the largest memory-safe block.

    The r3 silicon sweep showed solver TFLOPS rising ~8× from block 1024
    to 8192 (larger blocks = bigger MXU gemms and fewer sequentially-
    lowered factorizations), so auto picks the smallest power of two that
    covers d — i.e. a single exact block whenever d fits — capped at 8192
    on accelerators (4096, the historical fixed default, on CPU, whose
    factorizations don't tile) and shrunk until the cached ridge inverses
    (d·b bytes) stay within a quarter of the HBM budget, the same envelope
    the gram-cache auto rule assumes."""
    if block_size != "auto":
        return int(block_size)
    cap = 4096 if jax.default_backend() == "cpu" else 8192
    b = min(cap, 1 << int(np.ceil(np.log2(max(d, 128)))))
    itemsize = jnp.dtype(config.default_dtype).itemsize
    while b > 128 and d * b * itemsize > config.hbm_budget_bytes // 4:
        b //= 2
    return b


class BlockLeastSquaresEstimator(LabelEstimator):
    def __init__(
        self,
        block_size="auto",
        num_iters: int = 1,
        lam: float = 0.0,
        fit_intercept: bool = True,
        checkpoint_dir: Optional[str] = None,
        stream: Optional[bool] = None,
        parallelism: str = "data",
    ):
        if parallelism not in ("data", "model"):
            raise ValueError("parallelism must be 'data' or 'model'")
        self.block_size = block_size
        self.num_iters = num_iters
        self.lam = lam
        self.fit_intercept = fit_intercept
        # Epoch-boundary solver checkpointing (orbax); resumes on refit.
        self.checkpoint_dir = checkpoint_dir
        # Host-streamed feature blocks (double-buffered H2D) for feature
        # matrices that exceed HBM; None = auto by size.
        self.stream = stream
        # "data": rows sharded, psum'd grams (the default). "model": the
        # d-axis shards across the mesh and residual chunks ride a ppermute
        # ring (linalg/ring_bcd.py). Measured guidance (tools/bench_ring.py
        # on the 8-device mesh, n=256 k=4 iters=2): ring 5.5x faster at
        # d=n·k and 17.7x at d=8·n·k — the ring shards the per-block
        # factorizations across chips while the data path REPLICATES each
        # post-psum b x b inverse on every chip, and it moves n·k/P-sized
        # residual chunks instead of psum'ing b x b grams. Prefer "model"
        # whenever d well exceeds n·k and features are dense; prefer
        # "data" for tall-skinny problems (n >> d), sparse features, or
        # when per-chip HBM can't hold an (n, d/P) column shard.
        self.parallelism = parallelism

    def _weights(self, Y: jnp.ndarray) -> Optional[jax.Array]:
        return None

    def partial_fit(self, data, labels, state=None, decay=None,
                    window=None, chunk_rows=None):
        """Fold one labeled batch into retained normal-equation
        accumulators (``workflow.online.OnlineState``). The online
        re-solve is the EXACT dense normal-equation solution (not the
        BCD approximation), so it requires the (d, d) gram to be
        materializable — the usual online regime (features already
        reduced by the frozen featurize prefix)."""
        from keystone_tpu.workflow.online import partial_fit_step

        return partial_fit_step(state, data, labels, decay=decay,
                                window=window, chunk_rows=chunk_rows)

    def solve_online(self, state) -> BlockLinearMapper:
        """Re-solve the retained accumulators as one dense block (the
        exact solution of the streamed problem), wrapped in the same
        ``BlockLinearMapper`` interface the batch fit produces."""
        W, b = state.solve(self.lam, fit_intercept=self.fit_intercept)
        return BlockLinearMapper([W], [(0, state.d)], b)

    def fit(self, data, labels) -> BlockLinearMapper:
        from keystone_tpu.utils.sparse import SparseBatch

        if isinstance(data, SparseBatch):
            if self.parallelism == "model":
                raise ValueError(
                    "model parallelism is a dense-feature path; sparse "
                    "features use the streamed data-parallel solve"
                )
            return self._fit_sparse(data, labels)
        if self.parallelism == "model":
            return self._fit_ring(data, labels)
        block_size = resolve_block_size(
            self.block_size, int(np.shape(data)[-1])
        )
        stream = self.stream
        itemsize = jnp.dtype(config.default_dtype).itemsize
        if stream is None:
            a_bytes = int(np.prod(np.shape(data))) * itemsize
            stream = a_bytes > config.hbm_budget_bytes // 2

        if stream:
            # Features stay in host RAM — the caller's array, uncopied and
            # unmodified: centering happens per block as it streams
            # (col_center), so peak memory is A + one block, never 2·A.
            X_host = np.asarray(data, dtype=config.default_dtype)
            Y = jnp.asarray(labels)
            weights = self._weights(Y)
            # Labels are placed ONCE (they ride the solve as B anyway);
            # centering derives on-device from that copy
            # (RowMatrix.centered) — no second label transfer.
            Ay = RowMatrix.from_array(Y)
            x_mean = y_mean = None
            if self.fit_intercept:
                # Same math and guard as the device path below (weighted
                # means with a wsum floor), computed host-side for X
                # (host-resident by contract); label means ride the
                # psum'd re-shard path so the streamed fit is invariant
                # to the LABELS' arrival placement.
                if weights is None:
                    x_mean = X_host.mean(axis=0, dtype=X_host.dtype)
                    y_mean = Ay.col_sums() / Ay.n
                else:
                    w_np = np.asarray(weights, dtype=X_host.dtype)
                    wsum = max(float(w_np.sum()), 1e-12)
                    # matvec, not (w[:,None] * X).sum(0): no X-sized temporary
                    # on the path that exists because X barely fits in RAM.
                    x_mean = (w_np @ X_host) / wsum
                    Aw = RowMatrix.from_array(weights[:, None])
                    y_mean = Ay.weighted_col_sums(Aw) / jnp.maximum(
                        Aw.col_sums()[0], 1e-12
                    )
            B = Ay if y_mean is None else Ay.centered(y_mean)
            W_blocks, blocks = block_coordinate_descent_streamed(
                X_host,
                B,
                block_size=block_size,
                num_iters=self.num_iters,
                lam=self.lam,
                row_weights=weights,
                checkpoint_dir=self.checkpoint_dir,
                col_center=None if x_mean is None else np.asarray(x_mean),
            )
            b = None
            if self.fit_intercept:
                W = jnp.concatenate(W_blocks, axis=0)
                b = jnp.asarray(y_mean) - jnp.asarray(
                    x_mean, dtype=W.dtype
                ) @ W
            return BlockLinearMapper(W_blocks, blocks, b)

        X = jnp.asarray(data)
        Y = jnp.asarray(labels)
        weights = self._weights(Y)
        from keystone_tpu.linalg.row_matrix import storage_dtype

        full = jnp.dtype(config.default_dtype)
        x_mean = y_mean = None
        if self.fit_intercept:
            # Weighted problems need weighted centering: the intercept of
            # weighted ridge absorbs the weighted means, b = ȳ_w − x̄_wᵀW.
            # The means ride the same re-shard + per-shard-sum + psum path
            # as the grams (RowMatrix.col_sums), so a fit over a sharded
            # batch is bit-identical to one over the same bytes on a
            # single device — no host-side fold, and no dependence on
            # whatever placement the features arrived with. Centering
            # derives on-device from the ONE placed copy
            # (RowMatrix.centered: subtract, re-zero pad rows, cast) —
            # no second host-to-device transfer of X.
            Ax = RowMatrix.from_array(X, dtype=X.dtype)
            Ay = RowMatrix.from_array(Y, dtype=Y.dtype)
            if weights is None:
                x_mean = Ax.col_sums() / Ax.n
                y_mean = Ay.col_sums() / Ay.n
            else:
                Aw = RowMatrix.from_array(
                    weights[:, None], dtype=weights.dtype
                )
                wsum = jnp.maximum(Aw.col_sums()[0], 1e-12)
                x_mean = Ax.weighted_col_sums(Aw) / wsum
                y_mean = Ay.weighted_col_sums(Aw) / wsum
            A = Ax.centered(x_mean, dtype=storage_dtype())
            B = Ay.centered(y_mean, dtype=full)
        else:
            A = RowMatrix.from_array(X, dtype=storage_dtype())
            B = RowMatrix.from_array(Y)
        W_blocks, blocks = block_coordinate_descent(
            A,
            B,
            block_size=block_size,
            num_iters=self.num_iters,
            lam=self.lam,
            row_weights=weights,
            checkpoint_dir=self.checkpoint_dir,
        )
        b = None
        if self.fit_intercept:
            W = jnp.concatenate(W_blocks, axis=0)
            b = y_mean - x_mean @ W
        return BlockLinearMapper(W_blocks, blocks, b)


    def _fit_ring(self, data, labels) -> BlockLinearMapper:
        """Model-parallel fit: columns of A shard across the mesh and the
        residual chunks ride a ppermute ring (no gram psum, no all-gather —
        see linalg/ring_bcd.py for the layout and comm accounting)."""
        from keystone_tpu.linalg import block_coordinate_descent_ring

        if self._weights(jnp.asarray(labels)) is not None:
            raise ValueError(
                "the ring solver has no per-row weighting; use "
                "parallelism='data' for the class-weighted problem"
            )
        if self.checkpoint_dir is not None or self.stream:
            # Refuse rather than silently drop resume/streaming semantics
            # the data-parallel path would have honored.
            raise ValueError(
                "checkpoint_dir/stream are data-parallel features; the ring "
                "solver keeps its d-shard resident and has no epoch "
                "checkpointing (block_size is likewise implicit: each chip's "
                "block is d / ring size)"
            )
        X = np.asarray(data, dtype=config.default_dtype)
        Y = np.asarray(labels, dtype=config.default_dtype)
        x_mean = y_mean = None
        if self.fit_intercept:
            x_mean = X.mean(axis=0)
            y_mean = Y.mean(axis=0)
            # Center in place on an owned copy: X - mean would hold a second
            # full (n, d) array on the path meant for the largest d. When the
            # input was a jax.Array, np.asarray gives a read-only zero-copy
            # view (X is not data yet not writeable) — copy in that case too.
            if X is data or not X.flags.writeable:
                X = np.array(X, copy=True)
            np.subtract(X, x_mean, out=X)
            Y = Y - y_mean
        W = block_coordinate_descent_ring(
            X, Y, num_iters=self.num_iters, lam=self.lam
        )
        b = None
        if self.fit_intercept:
            b = jnp.asarray(y_mean) - jnp.asarray(x_mean) @ W
        return BlockLinearMapper([W], [(0, X.shape[1])], b)

    def _fit_sparse(self, data, labels) -> BlockLinearMapper:
        """Large-vocab path: CSR features stream to the device one dense
        column block at a time (an (n, vocab) dense array never exists).

        The intercept is learned as the weight of an appended all-ones
        column (centering would destroy sparsity); with lam > 0 the
        intercept is therefore ridge-penalized too — a small documented
        deviation from the centered dense path, exact at lam = 0.
        """
        Y = jnp.asarray(labels)
        weights = self._weights(Y)
        A = data.append_ones() if self.fit_intercept else data
        B = RowMatrix.from_array(Y)
        W_blocks, blocks = block_coordinate_descent_streamed(
            A,
            B,
            block_size=resolve_block_size(self.block_size, data.shape[1]),
            num_iters=self.num_iters,
            lam=self.lam,
            row_weights=weights,
            checkpoint_dir=self.checkpoint_dir,
        )
        b = None
        if self.fit_intercept:
            last = W_blocks[-1]
            b = last[-1]
            if last.shape[0] == 1:  # the ones column was its own block
                W_blocks = W_blocks[:-1]
                blocks = blocks[:-1]
            else:
                s, e = blocks[-1]
                W_blocks = W_blocks[:-1] + [last[:-1]]
                blocks = blocks[:-1] + [(s, e - 1)]
        return BlockLinearMapper(W_blocks, blocks, b)


class BlockWeightedLeastSquaresEstimator(BlockLeastSquaresEstimator):
    """Class-rebalanced block least squares.

    Each example of class c gets weight
        w = (1 − mixture_weight) + mixture_weight · n / (k · n_c),
    i.e. mixture_weight interpolates between the unweighted problem (0) and
    fully class-balanced weighting (1). Reconstruction of the reference's
    `mixtureWeight` semantics [unverified — verify against
    nodes/learning/BlockWeightedLeastSquaresEstimator.scala].
    """

    def __init__(
        self,
        block_size="auto",
        num_iters: int = 1,
        lam: float = 0.0,
        mixture_weight: float = 0.5,
        fit_intercept: bool = True,
        checkpoint_dir: Optional[str] = None,
        stream: Optional[bool] = None,
        parallelism: str = "data",
    ):
        super().__init__(
            block_size,
            num_iters,
            lam,
            fit_intercept,
            checkpoint_dir,
            stream,
            parallelism,
        )
        self.mixture_weight = mixture_weight

    # Class-rebalanced weights need the class counts of the FULL label
    # set — a per-batch fold cannot know them, so the online contract is
    # nulled out (supports_partial_fit -> False; Pipeline.refit_stream
    # falls back to the counted full refit and KG105 warns statically).
    partial_fit = None
    solve_online = None

    def _weights(self, Y: jnp.ndarray) -> Optional[jax.Array]:
        if self.mixture_weight == 0.0:
            return None  # exactly the unweighted problem
        # Y may be centered; class identity is still the row-wise argmax of
        # the ±1 indicator encoding.
        classes = jnp.argmax(Y, axis=1)
        k = Y.shape[1]
        n = Y.shape[0]
        counts = jnp.bincount(classes, length=k).astype(Y.dtype)
        counts = jnp.maximum(counts, 1.0)
        per_class = (1.0 - self.mixture_weight) + self.mixture_weight * n / (
            k * counts
        )
        return per_class[classes]
