from keystone_tpu.nodes.learning.linear_mapper import (
    LinearMapEstimator,
    LinearMapper,
)
from keystone_tpu.nodes.learning.local_least_squares import (
    LocalLeastSquaresEstimator,
)

__all__ = [
    "LinearMapper",
    "LinearMapEstimator",
    "LocalLeastSquaresEstimator",
]
