from keystone_tpu.nodes.learning.linear_mapper import (
    LinearMapEstimator,
    LinearMapper,
)
from keystone_tpu.nodes.learning.local_least_squares import (
    LocalLeastSquaresEstimator,
)
from keystone_tpu.nodes.learning.block_least_squares import (
    BlockLeastSquaresEstimator,
    BlockLinearMapper,
    BlockWeightedLeastSquaresEstimator,
)
from keystone_tpu.nodes.learning.least_squares import (
    LeastSquaresEstimator,
    SolverChoice,
    choose_solver,
)
from keystone_tpu.nodes.learning.pca import (
    DistributedPCAEstimator,
    PCAEstimator,
    PCATransformer,
)
from keystone_tpu.nodes.learning.zca import ZCAWhitener, ZCAWhitenerEstimator
from keystone_tpu.nodes.learning.kmeans import (
    KMeansModel,
    KMeansPlusPlusEstimator,
)
from keystone_tpu.nodes.learning.gmm import (
    GaussianMixtureModel,
    GaussianMixtureModelEstimator,
)
from keystone_tpu.nodes.learning.naive_bayes import (
    NaiveBayesEstimator,
    NaiveBayesModel,
)
from keystone_tpu.nodes.learning.logistic_regression import (
    LogisticRegressionEstimator,
    LogisticRegressionModel,
)
from keystone_tpu.nodes.learning.lda import LinearDiscriminantAnalysis
from keystone_tpu.nodes.learning.kernels import (
    GaussianKernelGenerator,
    KernelGenerator,
    LinearKernelGenerator,
)
from keystone_tpu.nodes.learning.kernel_ridge import (
    KernelBlockLinearMapper,
    KernelRidgeRegression,
)

__all__ = [
    "LinearMapper",
    "LinearMapEstimator",
    "LocalLeastSquaresEstimator",
    "BlockLinearMapper",
    "BlockLeastSquaresEstimator",
    "BlockWeightedLeastSquaresEstimator",
    "LeastSquaresEstimator",
    "SolverChoice",
    "choose_solver",
    "PCAEstimator",
    "DistributedPCAEstimator",
    "PCATransformer",
    "ZCAWhitener",
    "ZCAWhitenerEstimator",
    "KMeansModel",
    "KMeansPlusPlusEstimator",
    "GaussianMixtureModel",
    "GaussianMixtureModelEstimator",
    "NaiveBayesModel",
    "NaiveBayesEstimator",
    "LogisticRegressionModel",
    "LogisticRegressionEstimator",
    "LinearDiscriminantAnalysis",
    "KernelGenerator",
    "GaussianKernelGenerator",
    "LinearKernelGenerator",
    "KernelRidgeRegression",
    "KernelBlockLinearMapper",
]
