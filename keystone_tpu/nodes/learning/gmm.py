"""Diagonal-covariance Gaussian mixture fit by EM.

Ref: src/main/scala/nodes/learning/GaussianMixtureModel.scala —
`GaussianMixtureModelEstimator` (Breeze EM) and the EncEval-backed external
variant used for Fisher vectors; diagonal covariances (SURVEY.md §2.4,
§3.4) [unverified].

TPU lowering: each EM sweep is responsibilities (log-space gemm-shaped
computation + logsumexp) and moment re-estimation (two MXU gemms), scanned
with lax.fori_loop into a single XLA program. This is the pure-TPU GMM; the
C++ EncEval-parity implementation lives in keystone_tpu/native.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from keystone_tpu.config import config
from keystone_tpu.nodes.learning.kmeans import _fit_kmeans, _sq_dists
from keystone_tpu.workflow import Estimator, Transformer


class GaussianMixtureModel(Transformer):
    """Fitted GMM. As a transformer it emits per-component soft assignments
    (responsibilities) — the quantity Fisher-vector encoding consumes."""

    def __init__(self, weights, means, variances):
        self.weights = jnp.asarray(weights)  # (k,)
        self.means = jnp.asarray(means)  # (k, d)
        self.variances = jnp.asarray(variances)  # (k, d)

    def log_likelihoods(self, X):
        """(n, k) log p(x | component j) + log w_j."""
        X = jnp.asarray(X)
        inv = 1.0 / self.variances  # (k, d)
        # Expand ||(x - μ)/σ||² into gemm-shaped terms.
        quad = (
            (X * X) @ inv.T
            - 2.0 * X @ (self.means * inv).T
            + jnp.sum(self.means * self.means * inv, axis=1)
        )
        log_det = jnp.sum(jnp.log(self.variances), axis=1)
        d = X.shape[1]
        log_norm = -0.5 * (d * jnp.log(2 * jnp.pi) + log_det)
        return jnp.log(self.weights) + log_norm - 0.5 * quad

    def apply_batch(self, X):
        ll = self.log_likelihoods(X)
        return jax.nn.softmax(ll, axis=-1)

    def predict(self, X):
        return jnp.argmax(self.log_likelihoods(X), axis=-1)


@partial(jax.jit, static_argnames=("k", "max_iters"))
def _fit_gmm(X, key, k: int, max_iters: int, min_var: float):
    n, d = X.shape

    # Init from a short k-means run.
    centers = _fit_kmeans(X, key, k, 5)
    assign = jnp.argmin(_sq_dists(X, centers), axis=1)
    onehot = jax.nn.one_hot(assign, k, dtype=X.dtype)
    counts = jnp.maximum(onehot.sum(axis=0), 1.0)
    weights0 = counts / n
    means0 = (onehot.T @ X) / counts[:, None]
    ex2 = (onehot.T @ (X * X)) / counts[:, None]
    vars0 = jnp.maximum(ex2 - means0**2, min_var)

    def em(_i, carry):
        weights, means, variances = carry
        inv = 1.0 / variances
        quad = (
            (X * X) @ inv.T
            - 2.0 * X @ (means * inv).T
            + jnp.sum(means * means * inv, axis=1)
        )
        log_norm = -0.5 * (
            d * jnp.log(2 * jnp.pi) + jnp.sum(jnp.log(variances), axis=1)
        )
        log_r = jnp.log(weights) + log_norm - 0.5 * quad
        r = jax.nn.softmax(log_r, axis=-1)  # (n, k)
        nk = jnp.maximum(r.sum(axis=0), 1e-6)
        new_means = (r.T @ X) / nk[:, None]
        new_ex2 = (r.T @ (X * X)) / nk[:, None]
        new_vars = jnp.maximum(new_ex2 - new_means**2, min_var)
        return nk / n, new_means, new_vars

    return jax.lax.fori_loop(0, max_iters, em, (weights0, means0, vars0))


class GaussianMixtureModelEstimator(Estimator):
    def __init__(
        self,
        k: int,
        max_iters: int = 50,
        min_var: float = 1e-4,
        seed: int = 0,
    ):
        self.k = k
        self.max_iters = max_iters
        self.min_var = min_var
        self.seed = seed

    def fit(self, data) -> GaussianMixtureModel:
        X = jnp.asarray(data, dtype=config.default_dtype)
        w, m, v = _fit_gmm(
            X, jax.random.PRNGKey(self.seed), self.k, self.max_iters, self.min_var
        )
        return GaussianMixtureModel(w, m, v)
