"""Solver-selecting least squares — the node-level-optimizable estimator.

Ref: src/main/scala/nodes/learning/LeastSquaresEstimator.scala — an
`Optimizable` estimator advertising {local, normal-equations/TSQR, block}
implementations; a cost model picks one from data stats (n, d, k, cluster
size) at optimization time (SURVEY.md §2.4, §3.5) [unverified].

The cost model here is re-grounded in TPU reality (SURVEY.md §7 hard part
5: "the algorithm carries over, the constants don't"):

- gram memory: normal equations materialize a (d, d) gram — must fit HBM
  alongside the data shard; past that, block coordinate descent streams
  feature blocks.
- conditioning: TSQR costs ~2× normal equations but squares neither the
  condition number nor the gram storage on the augmented system.
- tiny problems: one un-sharded solve avoids collective overhead.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from keystone_tpu.config import config
from keystone_tpu.nodes.learning.block_least_squares import (
    BlockLeastSquaresEstimator,
)
from keystone_tpu.nodes.learning.linear_mapper import LinearMapEstimator
from keystone_tpu.nodes.learning.local_least_squares import (
    LocalLeastSquaresEstimator,
)
from keystone_tpu.workflow import LabelEstimator, Transformer


@dataclass
class SolverChoice:
    name: str  # "local" | "normal" | "block"
    reason: str


def choose_solver(
    n: int,
    d: int,
    k: int,
    hbm_budget_bytes: int | None = None,
    block_size: int = 4096,
) -> SolverChoice:
    if hbm_budget_bytes:
        hbm = hbm_budget_bytes
    else:
        # The budget the RUNTIME reports (TPU bytes_limit) when it does;
        # config.hbm_budget_bytes otherwise — the same device-first
        # resolution the auto-cache rule and the resource planner use, so
        # every cost-model consumer prices against one budget.
        from keystone_tpu.utils.metrics import device_hbm_bytes

        hbm = device_hbm_bytes()
    bytes_per = 4  # f32
    if n * d * bytes_per < 1 << 24 and d <= 2048:
        return SolverChoice("local", f"tiny problem (n={n}, d={d})")
    # Normal equations materialize the (d, d) gram plus the replicated (d, k)
    # solution and rhs; all must sit in HBM next to the data shard.
    dense_bytes = (d * d + 2 * d * k) * bytes_per
    if dense_bytes <= hbm // 8 and d <= 32768:
        return SolverChoice(
            "normal", f"gram+solution fit HBM ({dense_bytes >> 20} MiB)"
        )
    return SolverChoice(
        "block",
        f"(d={d}, k={k}) too large for a dense gram; "
        f"streaming {block_size}-col blocks",
    )


class LeastSquaresEstimator(LabelEstimator):
    """Picks the concrete solver by cost model at fit time.

    `num_iters`/`block_size` only apply when the block solver is chosen.
    """

    # Fit-time diagnostic, not identity — mutating it must not change the
    # content signature between executions.
    _signature_exclude = ("last_choice",)

    def __init__(
        self,
        lam: float = 0.0,
        block_size: int = 4096,
        num_iters: int = 3,
        hbm_budget_bytes: int | None = None,
    ):
        self.lam = lam
        self.block_size = block_size
        self.num_iters = num_iters
        self.hbm_budget_bytes = hbm_budget_bytes
        self.last_choice: SolverChoice | None = None

    def optimize_node(self, data_shape, labels_shape=None):
        """Node-level optimization hook (workflow.rules.NodeOptimizationRule):
        commit to a concrete solver from the dataset shapes at graph-optimize
        time. Returns self when shape info is insufficient (fit-time dispatch
        then still applies)."""
        if len(data_shape) != 2:
            return self
        n, d = int(data_shape[0]), int(data_shape[1])
        if labels_shape is None:
            return self  # label width unknown: defer to fit-time dispatch
        k = int(labels_shape[1]) if len(labels_shape) > 1 else 1
        choice = choose_solver(n, d, k, self.hbm_budget_bytes, self.block_size)
        self.last_choice = choice
        return self._concrete(choice)

    def _concrete(self, choice: SolverChoice) -> LabelEstimator:
        """THE SolverChoice -> concrete-estimator mapping, shared by
        ``optimize_node`` (graph-optimize-time dispatch) and ``fit``
        (fit-time dispatch): a new solver added to one path can no longer
        be missed by the other."""
        if choice.name == "local":
            return LocalLeastSquaresEstimator(self.lam)
        if choice.name == "normal":
            return LinearMapEstimator(self.lam)
        if choice.name == "block":
            return BlockLeastSquaresEstimator(
                block_size=self.block_size,
                num_iters=self.num_iters,
                lam=self.lam,
            )
        raise ValueError(f"unknown solver choice {choice.name!r}")

    def partial_fit(self, data, labels, state=None, decay=None,
                    window=None, chunk_rows=None):
        """Fold one labeled batch into retained normal-equation
        accumulators. The fold is solver-independent (gram/AᵀB running
        sums); ``solve_online`` always re-solves via the normal-equation
        Cholesky path — the one incremental-exact member of the solver
        menu — regardless of what the batch cost model would pick."""
        from keystone_tpu.workflow.online import partial_fit_step

        return partial_fit_step(state, data, labels, decay=decay,
                                window=window, chunk_rows=chunk_rows)

    def solve_online(self, state):
        from keystone_tpu.nodes.learning.linear_mapper import LinearMapper

        self.last_choice = SolverChoice(
            "normal", "online partial_fit re-solve (gram/AᵀB running sums)"
        )
        W, b = state.solve(self.lam)
        return LinearMapper(W, b)

    def fit(self, data, labels) -> Transformer:
        X = jnp.asarray(data)
        Y = jnp.asarray(labels)
        n, d = X.shape
        k = Y.shape[1] if Y.ndim > 1 else 1
        choice = choose_solver(
            n, d, k, self.hbm_budget_bytes, self.block_size
        )
        self.last_choice = choice
        return self._concrete(choice).fit(X, Y)
