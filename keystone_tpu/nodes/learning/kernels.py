"""Kernel generators.

Ref: src/main/scala/nodes/learning/KernelMatrix.scala /
GaussianKernelGenerator (SURVEY.md §2.4 kernel ridge row) [unverified].
A kernel generator produces gemm-shaped kernel blocks on demand — the
KernelMatrix of the reference becomes block computation fused into the
consumer, never an n×n array in memory.
"""

from __future__ import annotations

import jax.numpy as jnp


def pairwise_sq_dists(X, Z):
    """||x − z||² for all pairs, gemm-shaped (MXU-friendly), clamped ≥ 0
    against cancellation. The single source of truth for this expansion."""
    sq = (
        jnp.sum(X * X, axis=1, keepdims=True)
        - 2.0 * X @ Z.T
        + jnp.sum(Z * Z, axis=1)
    )
    return jnp.maximum(sq, 0.0)


class KernelGenerator:
    def block(self, X, Z):
        """Kernel block k(X, Z) of shape (len(X), len(Z))."""
        raise NotImplementedError


class GaussianKernelGenerator(KernelGenerator):
    """k(x, z) = exp(−gamma ||x − z||²)."""

    def __init__(self, gamma: float):
        self.gamma = gamma

    def block(self, X, Z):
        return jnp.exp(-self.gamma * pairwise_sq_dists(X, Z))


class LinearKernelGenerator(KernelGenerator):
    def block(self, X, Z):
        return X @ Z.T
