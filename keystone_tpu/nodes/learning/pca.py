"""PCA: local SVD and distributed (TSQR-based) variants.

Ref: src/main/scala/nodes/learning/PCA.scala — `PCAEstimator` (driver-local
SVD via Breeze/LAPACK gesdd) and `DistributedPCAEstimator` (TSQR-based),
both producing `PCATransformer` projecting onto the top components
(SURVEY.md §2.4, §3.4: PCA of SIFT descriptors) [unverified].

TPU lowering: the local variant is one on-device SVD of the centered data;
the distributed variant reduces the row-sharded data to its (d, d) R factor
by TSQR (all_gather over ICI), then SVDs the small R — identical right
singular vectors, no n×d gather.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from keystone_tpu.linalg import RowMatrix, tsqr_r
from keystone_tpu.workflow import Estimator, Transformer


class PCATransformer(Transformer):
    def __init__(self, components: jax.Array, mean: jax.Array | None = None):
        # components: (d, dims) — columns are principal directions.
        self.components = jnp.asarray(components)
        self.mean = None if mean is None else jnp.asarray(mean)

    def signature(self):
        # Content-stable from the fitted parameters: prefixes THROUGH a
        # fitted PCA stay persistable, so downstream fits (the flagship
        # solver) can hit the cross-process cache. Computed once — this is
        # called on every executor walk and the fingerprint costs a
        # device-to-host fetch.
        sig = getattr(self, "_sig", None)
        if sig is None:
            import numpy as np

            from keystone_tpu.workflow.fingerprint import array_fingerprint

            sig = self.stable_signature(
                array_fingerprint(np.asarray(self.components)),
                None
                if self.mean is None
                else array_fingerprint(np.asarray(self.mean)),
            )
            self._sig = sig
        return sig

    def apply_batch(self, X):
        if self.mean is not None:
            X = X - self.mean
        return X @ self.components


def _components_from_r(R: jax.Array, dims: int) -> jax.Array:
    # Right singular vectors of the data = eigenvectors of RᵀR.
    _u, _s, vt = jnp.linalg.svd(R, full_matrices=False)
    return vt[:dims].T


class PCAEstimator(Estimator):
    """Un-sharded SVD PCA (the sample sizes the reference uses fit easily)."""

    def __init__(self, dims: int, center: bool = True):
        self.dims = dims
        self.center = center

    def fit(self, data) -> PCATransformer:
        X = jnp.asarray(data)
        mean = X.mean(axis=0) if self.center else None
        Xc = X - mean if self.center else X
        _u, _s, vt = jnp.linalg.svd(Xc, full_matrices=False)
        return PCATransformer(vt[: self.dims].T, mean)


class DistributedPCAEstimator(Estimator):
    """PCA via TSQR of the row-sharded (centered) data matrix."""

    def __init__(self, dims: int, center: bool = True):
        self.dims = dims
        self.center = center

    def fit(self, data) -> PCATransformer:
        X = jnp.asarray(data)
        mean = X.mean(axis=0) if self.center else None
        Xc = X - mean if self.center else X
        R = tsqr_r(RowMatrix.from_array(Xc))
        return PCATransformer(_components_from_r(R, self.dims), mean)
