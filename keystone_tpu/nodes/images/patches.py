"""Patch extraction nodes.

Ref: src/main/scala/nodes/images/{RandomPatcher,Windower,
CenterCornerPatcher}.scala (SURVEY.md §2.5) [unverified].
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from keystone_tpu.workflow import Transformer


class RandomPatcher(Transformer):
    """Extract `num_patches` random (size × size) patches from the batch —
    the filter-learning sampler of RandomPatchCifar. Deterministic by seed.

    Host-side index generation (tiny), one device gather (fast).
    """

    jittable = False  # output count depends on num_patches, not batch size
    row_independent = False  # output rows are drawn across the whole batch

    def __init__(self, num_patches: int, patch_size: int, seed: int = 0):
        self.num_patches = num_patches
        self.patch_size = patch_size
        self.seed = seed

    def apply_batch(self, X):
        X = jnp.asarray(X)
        n, h, w, _c = X.shape
        p = self.patch_size
        rng = np.random.default_rng(self.seed)
        img_idx = rng.integers(0, n, size=self.num_patches)
        tops = rng.integers(0, h - p + 1, size=self.num_patches)
        lefts = rng.integers(0, w - p + 1, size=self.num_patches)
        rows = tops[:, None] + np.arange(p)[None, :]  # (np, p)
        cols = lefts[:, None] + np.arange(p)[None, :]
        # Advanced-indexing gather: (num_patches, p, p, c).
        return X[img_idx[:, None, None], rows[:, :, None], cols[:, None, :], :]


class Windower(Transformer):
    """All (size × size) windows at `stride` — the im2col view, exposed as a
    node for featurizers that want explicit patches."""

    # n images fan out to n·windows rows: slicing a padded batch's output
    # [:n] would return the wrong rows, so bucketed serving refuses it.
    row_independent = False

    def __init__(self, stride: int, window_size: int):
        self.stride = stride
        self.window_size = window_size

    def apply_batch(self, X):
        n, h, w, c = X.shape
        p, s = self.window_size, self.stride
        out_h = (h - p) // s + 1
        out_w = (w - p) // s + 1
        i0 = (jnp.arange(out_h) * s)[:, None] + jnp.arange(p)[None, :]
        j0 = (jnp.arange(out_w) * s)[:, None] + jnp.arange(p)[None, :]
        # (n, out_h, p, w, c) → (n, out_h, out_w, p, p, c)
        rows = X[:, i0, :, :]
        wins = rows[:, :, :, j0, :]
        wins = jnp.moveaxis(wins, 3, 2)  # windows before in-patch rows? see below
        # resulting layout: (n, out_h, out_w, p, p, c)
        return wins.reshape(n * out_h * out_w, p, p, c)


class Cropper(Transformer):
    """Fixed crop (Ref: nodes/images/Cropper.scala [unverified])."""

    def __init__(self, top: int, left: int, height: int, width: int):
        if min(top, left) < 0 or min(height, width) <= 0:
            raise ValueError(
                f"invalid crop (top={top}, left={left}, h={height}, w={width})"
            )
        self.top = top
        self.left = left
        self.height = height
        self.width = width

    def apply_batch(self, X):
        if self.top + self.height > X.shape[1] or self.left + self.width > X.shape[2]:
            raise ValueError(
                f"crop {self.top}+{self.height} x {self.left}+{self.width} "
                f"exceeds image {X.shape[1]}x{X.shape[2]}"
            )
        return X[
            :,
            self.top : self.top + self.height,
            self.left : self.left + self.width,
            :,
        ]


class CenterCornerPatcher(Transformer):
    """Center + four corner crops, optionally horizontally flipped — the
    test-time augmentation of the ImageNet pipeline. Emits (n·views, s, s, c)
    with views grouped per image."""

    row_independent = False  # n images emit n·views rows

    def __init__(self, crop_size: int, with_flips: bool = True):
        self.crop_size = crop_size
        self.with_flips = with_flips

    @property
    def num_views(self) -> int:
        return 10 if self.with_flips else 5

    def apply_batch(self, X):
        n, h, w, _c = X.shape
        s = self.crop_size
        if s > h or s > w:
            raise ValueError(f"crop {s} exceeds image {h}x{w}")
        ct, cl = (h - s) // 2, (w - s) // 2
        crops = [
            X[:, :s, :s, :],
            X[:, :s, w - s :, :],
            X[:, h - s :, :s, :],
            X[:, h - s :, w - s :, :],
            X[:, ct : ct + s, cl : cl + s, :],
        ]
        if self.with_flips:
            crops += [c[:, :, ::-1, :] for c in crops]
        stacked = jnp.stack(crops, axis=1)  # (n, views, s, s, c)
        return stacked.reshape(-1, s, s, X.shape[-1])
