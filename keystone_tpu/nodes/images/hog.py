"""Histogram-of-oriented-gradients descriptors.

Ref: src/main/scala/nodes/images/HogExtractor.scala (SURVEY.md §2.5, listed
low-confidence) [unverified]. Standard HOG: per-pixel gradient orientation
soft-binned into `num_bins` channels, summed over cells, L2-hys normalized
over 2×2 cell blocks.

TPU lowering: the orientation channels are one fused elementwise program
over the batch; cell pooling is reduce_window; everything jits into a
single XLA computation.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from keystone_tpu.utils.image import grayscale, orientation_maps
from keystone_tpu.workflow import Transformer


class HogExtractor(Transformer):
    def __init__(
        self,
        cell_size: int = 8,
        num_bins: int = 9,
        clip: float = 0.2,
        eps: float = 1e-6,
    ):
        self.cell_size = cell_size
        self.num_bins = num_bins
        self.clip = clip
        self.eps = eps

    def apply_batch(self, X):
        if X.shape[-1] != 1:
            X = grayscale(X)
        # Unsigned orientations ([0, π)), edge-clamped gradients.
        channels = orientation_maps(X[..., 0], self.num_bins, signed=False)
        cs = self.cell_size
        cells = lax.reduce_window(
            channels, 0.0, lax.add, (1, cs, cs, 1), (1, cs, cs, 1), "VALID"
        )  # (n, ch, cw, bins)
        # 2x2-cell block normalization with clipping (L2-hys).
        n, ch, cw, nb = cells.shape
        if ch < 2 or cw < 2:
            raise ValueError(
                f"image too small for HOG: {X.shape[1]}x{X.shape[2]} gives a "
                f"{ch}x{cw} cell grid (need >= 2x2 at cell_size="
                f"{self.cell_size})"
            )
        blocks = jnp.concatenate(
            [
                cells[:, :-1, :-1],
                cells[:, :-1, 1:],
                cells[:, 1:, :-1],
                cells[:, 1:, 1:],
            ],
            axis=-1,
        )  # (n, ch-1, cw-1, 4*bins)
        norm = jnp.linalg.norm(blocks, axis=-1, keepdims=True)
        blocks = blocks / jnp.maximum(norm, self.eps)
        blocks = jnp.minimum(blocks, self.clip)
        norm2 = jnp.linalg.norm(blocks, axis=-1, keepdims=True)
        blocks = blocks / jnp.maximum(norm2, self.eps)
        return blocks.reshape(n, -1)
