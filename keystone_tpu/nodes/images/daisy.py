"""DAISY-style dense descriptors.

Ref: src/main/scala/nodes/images/DaisyExtractor.scala (SURVEY.md §2.5,
listed low-confidence) [unverified]. DAISY: per-pixel orientation maps
smoothed at increasing scales, sampled at a center point plus rings of
points, each sample an L2-normalized orientation histogram.

The smoothing here approximates Gaussians with iterated mean filters
(three box passes ≈ Gaussian), keeping the whole extractor one jittable
XLA program over the batch.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from keystone_tpu.utils.image import grayscale, orientation_maps
from keystone_tpu.workflow import Transformer


def _box_smooth(x, radius: int, passes: int = 3):
    if radius <= 0:
        return x
    size = 2 * radius + 1
    for _ in range(passes):
        s = lax.reduce_window(
            x, 0.0, lax.add, (1, size, size, 1), (1, 1, 1, 1), "SAME"
        )
        cnt = lax.reduce_window(
            jnp.ones_like(x[..., :1]),
            0.0,
            lax.add,
            (1, size, size, 1),
            (1, 1, 1, 1),
            "SAME",
        )
        x = s / cnt
    return x


class DaisyExtractor(Transformer):
    def __init__(
        self,
        step: int = 8,
        radius: int = 12,
        rings: int = 2,
        ring_points: int = 8,
        num_bins: int = 8,
        eps: float = 1e-8,
    ):
        self.step = step
        self.radius = radius
        self.rings = rings
        self.ring_points = ring_points
        self.num_bins = num_bins
        self.eps = eps

    @property
    def descriptor_dim(self) -> int:
        return (1 + self.rings * self.ring_points) * self.num_bins

    def apply_batch(self, X):
        if X.shape[-1] != 1:
            X = grayscale(X)
        g = X[..., 0]
        n, h, w = g.shape
        # Signed orientations ([0, 2π)), edge-clamped gradients.
        maps = orientation_maps(g, self.num_bins, signed=True)

        # One smoothing scale per ring (center uses the finest).
        scales = [
            _box_smooth(maps, max(1, self.radius * (r + 1) // (2 * self.rings)))
            for r in range(self.rings + 1)
        ]

        # Sample grid: keypoints away from the border by `radius`.
        ys = np.arange(self.radius, h - self.radius, self.step)
        xs = np.arange(self.radius, w - self.radius, self.step)
        if len(ys) == 0 or len(xs) == 0:
            raise ValueError(
                f"image ({h}x{w}) smaller than the DAISY radius {self.radius}"
            )
        ky, kx = np.meshgrid(ys, xs, indexing="ij")
        ky = ky.reshape(-1)
        kx = kx.reshape(-1)

        samples = [scales[0][:, ky, kx, :]]  # center (n, K, bins)
        for r in range(1, self.rings + 1):
            rad = self.radius * r / self.rings
            for p in range(self.ring_points):
                ang = 2 * np.pi * p / self.ring_points
                oy = np.clip(np.round(ky + rad * np.sin(ang)).astype(int), 0, h - 1)
                ox = np.clip(np.round(kx + rad * np.cos(ang)).astype(int), 0, w - 1)
                samples.append(scales[r][:, oy, ox, :])
        desc = jnp.stack(samples, axis=2)  # (n, K, points, bins)
        norm = jnp.linalg.norm(desc, axis=-1, keepdims=True)
        desc = desc / jnp.maximum(norm, self.eps)
        K = len(ky)
        return desc.reshape(n, K, self.descriptor_dim)
