from keystone_tpu.nodes.images.convolver import Convolver
from keystone_tpu.nodes.images.pooling import Pooler, SymmetricRectifier
from keystone_tpu.nodes.images.patches import (
    CenterCornerPatcher,
    Cropper,
    RandomPatcher,
    Windower,
)
from keystone_tpu.nodes.images.lcs import LCSExtractor
from keystone_tpu.nodes.images.hog import HogExtractor
from keystone_tpu.nodes.images.daisy import DaisyExtractor
from keystone_tpu.nodes.images.pixels import (
    GrayScaler,
    ImageVectorizer,
    PixelScaler,
)

__all__ = [
    "Convolver",
    "Pooler",
    "SymmetricRectifier",
    "RandomPatcher",
    "CenterCornerPatcher",
    "Cropper",
    "Windower",
    "LCSExtractor",
    "HogExtractor",
    "DaisyExtractor",
    "GrayScaler",
    "PixelScaler",
    "ImageVectorizer",
]
