from keystone_tpu.nodes.images.convolver import Convolver
from keystone_tpu.nodes.images.pooling import Pooler, SymmetricRectifier
from keystone_tpu.nodes.images.patches import (
    CenterCornerPatcher,
    RandomPatcher,
    Windower,
)
from keystone_tpu.nodes.images.lcs import LCSExtractor
from keystone_tpu.nodes.images.pixels import (
    GrayScaler,
    ImageVectorizer,
    PixelScaler,
)

__all__ = [
    "Convolver",
    "Pooler",
    "SymmetricRectifier",
    "RandomPatcher",
    "CenterCornerPatcher",
    "Windower",
    "LCSExtractor",
    "GrayScaler",
    "PixelScaler",
    "ImageVectorizer",
]
