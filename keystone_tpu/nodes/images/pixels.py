"""Pixel-level nodes.

Ref: src/main/scala/nodes/images/{GrayScaler,PixelScaler,ImageVectorizer}
.scala (SURVEY.md §2.5) [unverified].
"""

from __future__ import annotations

from keystone_tpu.utils.image import grayscale, vectorize
from keystone_tpu.workflow import Transformer


class GrayScaler(Transformer):
    def signature(self):
        return self.stable_signature()

    def apply_batch(self, X):
        return grayscale(X)


class PixelScaler(Transformer):
    """uint8 pixel range → [0, 1] floats."""

    def __init__(self, scale: float = 255.0):
        self.scale = scale

    def signature(self):
        return self.stable_signature(self.scale)

    def apply_batch(self, X):
        return X / self.scale


class ImageVectorizer(Transformer):
    def signature(self):
        return self.stable_signature()

    def apply_batch(self, X):
        return vectorize(X)
