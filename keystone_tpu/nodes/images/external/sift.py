"""Dense SIFT extractor node: native (C++) and on-chip (XLA) backends.

Ref: src/main/scala/nodes/images/external/SIFTExtractor.scala — the JNI
wrapper transformer around VLFeat.getSIFTs (SURVEY.md §2.5, §3.4)
[unverified]. Input NHWC (or NHW1) grayscale batch; output
(n, num_keypoints, 128) descriptor sets — the dense grid is static per
image shape, so downstream stages see fixed shapes (no ragged batching).

Backends with identical math (parity-tested against each other):
- "native": the clean-room C++ kernel (reference-parity path; host CPU);
- "xla": ops/sift_xla.py — grouped 1-D convolutions on the default
  backend. On TPU this removes the last host-side featurization stage
  (the host keeps only JPEG decode) and lets the SIFT→PCA→FV branch fuse
  into device programs.
"""

from __future__ import annotations

import numpy as np

from keystone_tpu import native
from keystone_tpu.workflow import Transformer


class SIFTExtractor(Transformer):
    def __init__(
        self,
        step: int = 4,
        bin_size: int = 4,
        scale_factor: float = 1.0,
        backend: str = "native",
    ):
        if backend not in ("native", "xla"):
            raise ValueError(f"unknown backend {backend!r}")
        self.step = step
        self.bin_size = bin_size
        self.scale_factor = scale_factor
        self.backend = backend
        # Host/native compute breaks jittable chains; the xla backend is a
        # pure jnp program and fuses with downstream device stages.
        self.jittable = backend == "xla"
        if backend == "native" and not native.available():
            raise RuntimeError(
                "native library unavailable "
                f"(build error: {native.build_error()}); "
                "run `make` in keystone_tpu/native, or use backend='xla'"
            )

    # Descriptor-math version: bump whenever either backend's numerics
    # change (r4: HIGHEST-precision convs + sub-floor norm guard in the xla
    # path). Without it, disk-cached fits keyed on old drifted descriptors
    # would keep being served — the cache key deliberately excludes code.
    DESCRIPTOR_VERSION = 2

    def signature(self):
        # Backend excluded: it changes where identical math runs, not the
        # result (same convention as FisherVector.signature).
        return self.stable_signature(
            self.step, self.bin_size, self.scale_factor, self.DESCRIPTOR_VERSION
        )

    def apply_batch(self, X):
        # Nested-list inputs need one dtype-free asarray before the ellipsis
        # index below; ndarrays AND jax tracers (this node is jittable on the
        # xla backend) already index natively and must pass through untouched.
        if not hasattr(X, "ndim"):
            X = np.asarray(X)
        if np.ndim(X) == 4:
            if np.shape(X)[-1] != 1:
                raise ValueError("SIFTExtractor expects grayscale input")
            X = X[..., 0]
        if self.backend == "xla":
            import jax.numpy as jnp

            from keystone_tpu.ops.sift_xla import dense_sift_xla

            descs = dense_sift_xla(
                jnp.asarray(X), step=self.step, bin_size=self.bin_size
            )
        else:
            descs = native.dense_sift(
                np.asarray(X, dtype=np.float32),
                step=self.step,
                bin_size=self.bin_size,
            )
        if self.scale_factor != 1.0:
            descs = descs * self.scale_factor
        return descs
