"""Dense SIFT extractor node backed by the native C++ library.

Ref: src/main/scala/nodes/images/external/SIFTExtractor.scala — the JNI
wrapper transformer around VLFeat.getSIFTs (SURVEY.md §2.5, §3.4)
[unverified]. Input NHWC (or NHW1) grayscale batch; output
(n, num_keypoints, 128) descriptor sets — the dense grid is static per
image shape, so downstream stages see fixed shapes (no ragged batching).
"""

from __future__ import annotations

import numpy as np

from keystone_tpu import native
from keystone_tpu.workflow import Transformer


class SIFTExtractor(Transformer):
    jittable = False  # host/native compute; output feeds device stages

    def __init__(self, step: int = 4, bin_size: int = 4, scale_factor: float = 1.0):
        self.step = step
        self.bin_size = bin_size
        self.scale_factor = scale_factor
        if not native.available():
            raise RuntimeError(
                "native library unavailable "
                f"(build error: {native.build_error()}); "
                "run `make` in keystone_tpu/native"
            )

    def signature(self):
        return self.stable_signature(self.step, self.bin_size, self.scale_factor)

    def apply_batch(self, X):
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 4:
            if X.shape[-1] != 1:
                raise ValueError("SIFTExtractor expects grayscale input")
            X = X[..., 0]
        descs = native.dense_sift(X, step=self.step, bin_size=self.bin_size)
        if self.scale_factor != 1.0:
            descs = descs * self.scale_factor
        return descs
