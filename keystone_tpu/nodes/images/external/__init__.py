from keystone_tpu.nodes.images.external.sift import SIFTExtractor
from keystone_tpu.nodes.images.external.fisher_vector import (
    FisherVector,
    GMMFisherVectorEstimator,
)

__all__ = ["SIFTExtractor", "FisherVector", "GMMFisherVectorEstimator"]
