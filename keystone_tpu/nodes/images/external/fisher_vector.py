"""Fisher-vector encoding: native (EncEval-parity) and TPU backends.

Ref: src/main/scala/nodes/images/external/FisherVector.scala and the
GMM-fitting estimator around EncEval.{computeGMM, calcAndGetFVs}
(SURVEY.md §2.5, §3.4) [unverified].

Two backends with identical math:
- "native": the C++ library (capability parity with the reference's
  native path; OpenMP on the host).
- "tpu": batched jnp — responsibilities and both gradient blocks are
  einsums on the MXU, jitted and chunked over images. This is the
  performance path (SURVEY.md §2.3 rebuild note).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu import native
from keystone_tpu.config import config
from keystone_tpu.workflow import Estimator, Transformer


@partial(jax.jit, static_argnames=())
def _fv_tpu(X, w, mu, var):
    """X: (B, m, d) descriptor sets → (B, 2·k·d) raw Fisher vectors."""
    from keystone_tpu.ops.fv_common import fv_constants

    B, m, d = X.shape
    k = w.shape[0]
    w, inv, logw_norm, cm, cv = fv_constants(w, mu, var, m)
    # log N(x | mu_j, var_j) + log w_j, gemm-shaped.
    quad = (
        jnp.einsum("bmd,kd->bmk", X * X, inv)
        - 2.0 * jnp.einsum("bmd,kd->bmk", X, mu * inv)
        + jnp.sum(mu * mu * inv, axis=1)
    )
    log_r = logw_norm - 0.5 * quad  # (B, m, k)
    r = jax.nn.softmax(log_r, axis=-1)
    sigma = jnp.sqrt(var)  # (k, d)
    # gmu_jt = Σ_i r_ij (x_it − mu_jt)/sigma_jt
    rx = jnp.einsum("bmk,bmd->bkd", r, X)
    rsum = jnp.sum(r, axis=1)  # (B, k)
    gmu = (rx - rsum[..., None] * mu) / sigma
    # gvar_jt = Σ_i r_ij ((x−mu)²/var − 1)
    rx2 = jnp.einsum("bmk,bmd->bkd", r, X * X)
    gvar = (
        rx2 - 2.0 * mu * rx + rsum[..., None] * (mu * mu)
    ) * inv - rsum[..., None]
    out = jnp.concatenate(
        [(gmu * cm).reshape(B, -1), (gvar * cv).reshape(B, -1)], axis=-1
    )
    return out.astype(config.default_dtype)


class FisherVector(Transformer):
    """Encodes per-image descriptor sets (B, m, d) into (B, 2·k·d) FVs.

    Backends: "tpu" (XLA einsums), "pallas" (fused kernel keeping the
    responsibilities in VMEM — see keystone_tpu/ops/fisher_vector_pallas),
    "native" (C++ EncEval-parity path).
    """

    def __init__(self, weights, means, variances, backend: str = "tpu"):
        if backend not in ("tpu", "pallas", "native"):
            raise ValueError(f"unknown backend {backend!r}")
        self.weights = np.asarray(weights, dtype=np.float32)
        self.means = np.asarray(means, dtype=np.float32)
        self.variances = np.asarray(variances, dtype=np.float32)
        self.backend = backend
        from keystone_tpu.workflow.fingerprint import array_fingerprint

        # Content-stable from the fitted GMM (backend excluded: it changes
        # WHERE the math runs, not what the encoding is).
        self._sig = self.stable_signature(
            array_fingerprint(self.weights),
            array_fingerprint(self.means),
            array_fingerprint(self.variances),
        )
        self.jittable = backend in ("tpu", "pallas")
        self.uses_pallas = backend == "pallas"

    def apply_batch(self, X):
        if self.backend == "pallas":
            from keystone_tpu.ops import fisher_vectors_pallas

            return fisher_vectors_pallas(
                X, self.weights, self.means, self.variances
            )
        if self.backend == "tpu":
            return _fv_tpu(
                jnp.asarray(X),
                jnp.asarray(self.weights),
                jnp.asarray(self.means),
                jnp.asarray(self.variances),
            )
        X = np.asarray(X, dtype=np.float32)
        return np.stack(
            [
                native.fisher_vector(x, self.weights, self.means, self.variances)
                for x in X
            ]
        )

    def apply_sharded(self, X, layout):
        """The Pallas kernel on the sharded path. On a real TPU mesh the
        kernel has no SPMD partitioning rule, so GSPMD would gather the
        whole batch onto every core — instead it is wrapped in
        ``shard_map`` over the layout's data axis: each core runs the
        kernel on its own row shard (per-image math, so the concatenated
        shards are the full-batch answer). On interpret-mode backends
        (CPU tests) the kernel lowers to plain XLA ops that partition
        under GSPMD bit-identically to the single-device jitted walk, so
        the plain body is both correct and the bit-identity anchor."""
        if self.backend != "pallas" or jax.default_backend() != "tpu":
            return self.apply_batch(X)
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from keystone_tpu.ops import fisher_vectors_pallas

        def _kernel(x):
            return fisher_vectors_pallas(
                x, self.weights, self.means, self.variances
            )

        return shard_map(
            _kernel,
            mesh=layout.mesh,
            in_specs=P(layout.axis),
            out_specs=P(layout.axis),
            check_rep=False,
        )(X)


def fit_fisher_featurizer(
    front,
    train_images,
    pca_dims: int,
    gmm_k: int,
    em_iters: int = 20,
    sample_size: int = 100_000,
    backend: str = "tpu",
    seed: int = 0,
):
    """Fit one descriptor branch: front → PCA → FV → signed sqrt → L2.

    `front` is the descriptor extractor pipeline (SIFT or LCS); PCA and the
    GMM are fit on a flat descriptor sample from `train_images`. Shared by
    the VOC and ImageNet pipelines (their branches differ only in `front`).
    """
    import numpy as _np

    from keystone_tpu.nodes.learning import PCAEstimator
    from keystone_tpu.nodes.stats import SignedHellingerMapper
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.samplers import sample_rows
    from keystone_tpu.workflow import PipelineEnv

    def _assemble(pca, fv):
        return (
            front.and_then(pca)
            .and_then(fv)
            .and_then(SignedHellingerMapper())
            .and_then(L2Normalizer())
        )

    # These eager fits (dense SIFT/LCS over the sample + GMM EM) dominate a
    # flagship refit, and being OUTSIDE the graph they'd never hit the
    # executor's fit cache — so they get their own content-addressed disk
    # entry: front signatures + image fingerprint + hyperparams + numeric
    # salt. Any unstable part (custom front node) degrades to no caching.
    env = PipelineEnv.get()
    key = None
    if env.disk_cache is not None:
        from keystone_tpu.config import config as _config
        from keystone_tpu.workflow.fingerprint import (
            array_fingerprint,
            digest_tree,
        )

        try:
            from keystone_tpu.workflow.graph import structural_digest

            # Digest the WHOLE front graph (estimator + dataset nodes fold
            # in; anything id-based poisons to None) — a transformer-only
            # signature list would silently drop embedded fitted state.
            front_digest = structural_digest(
                front.graph, front.sink, source_token="branch-input"
            )
            images_fp = array_fingerprint(_np.asarray(train_images))
            key = (
                None
                if front_digest is None
                else digest_tree(
                    (
                        "fv-branch-v2",
                        front_digest,
                        images_fp,
                        pca_dims,
                        gmm_k,
                        em_iters,
                        sample_size,
                        backend,
                        seed,
                        # These fits only read the default compute dtype —
                        # solver-side knobs must not invalidate hours of
                        # SIFT+EM (see executor.d_of for the solver salt).
                        _config.default_dtype,
                    )
                )
            )
        except Exception as e:  # lint: broad-ok cache-key construction is best-effort; fits proceed uncached
            import logging

            logging.getLogger("keystone_tpu").warning(
                "fisher branch cache key construction failed (%s); "
                "branch fits will not be cached",
                e,
            )
            key = None
        if key is not None:
            cached = env.disk_cache.get(key)
            if cached is not None:
                return _assemble(*cached)

    descs = _np.asarray(front(train_images).get())  # (n, m, d)
    flat = sample_rows(
        descs.reshape(-1, descs.shape[-1]), sample_size, seed=seed
    )
    pca = PCAEstimator(dims=pca_dims).fit(flat)
    # The GMM only ever sees sample_size descriptors — PCA-transform the
    # sample, never the full n·m descriptor set.
    fv = GMMFisherVectorEstimator(
        k=gmm_k,
        em_iters=em_iters,
        sample_size=sample_size,
        backend=backend,
        seed=seed,
    ).fit(_np.asarray(pca(flat)))
    if key is not None:
        env.disk_cache.put(key, (pca, fv))
    return _assemble(pca, fv)


class GMMFisherVectorEstimator(Estimator):
    """Fits the GMM over sampled descriptors and returns the FisherVector
    transformer.

    fit() input: (B, m, d) descriptor sets or an (n, d) flat descriptor
    matrix; a flat descriptor sample is drawn for the EM.

    gmm_backend: "native" (C++ EM), "tpu" (jnp EM), or "auto" — native when
    the library built, otherwise the jnp twin (the two converge to the same
    mixture; see tests/test_native.py).
    """

    def __init__(
        self,
        k: int,
        em_iters: int = 25,
        sample_size: int = 100_000,
        backend: str = "tpu",
        gmm_backend: str = "auto",
        seed: int = 0,
    ):
        self.k = k
        self.em_iters = em_iters
        self.sample_size = sample_size
        self.backend = backend
        self.seed = seed
        if gmm_backend == "auto":
            gmm_backend = "native" if native.available() else "tpu"
        if gmm_backend == "native" and not native.available():
            raise RuntimeError(
                "native library unavailable "
                f"(build error: {native.build_error()}); "
                "run `make` in keystone_tpu/native or use gmm_backend='tpu'"
            )
        self.gmm_backend = gmm_backend

    def fit(self, descriptor_sets) -> FisherVector:
        from keystone_tpu.nodes.stats.samplers import sample_rows

        X = np.asarray(descriptor_sets, dtype=np.float32)
        flat = sample_rows(
            X.reshape(-1, X.shape[-1]), self.sample_size, seed=self.seed
        )
        if self.gmm_backend == "native":
            w, mu, var = native.gmm_fit(
                flat, k=self.k, iters=self.em_iters, seed=self.seed
            )
        else:
            from keystone_tpu.nodes.learning.gmm import (
                GaussianMixtureModelEstimator,
            )

            gmm = GaussianMixtureModelEstimator(
                k=self.k, max_iters=self.em_iters, seed=self.seed
            ).fit(flat)
            w, mu, var = gmm.weights, gmm.means, gmm.variances
        return FisherVector(w, mu, var, backend=self.backend)
