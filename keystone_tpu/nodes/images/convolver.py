"""Dense patch convolution.

Ref: src/main/scala/nodes/images/Convolver.scala — convolves images with a
filter bank via explicit im2col + BLAS gemm, optionally folding a ZCA
whitener into the filters (the RandomPatchCifar featurizer; SURVEY.md §2.5,
§3.1) [unverified].

TPU lowering: `lax.conv_general_dilated` — the MXU performs im2col+gemm
natively, so the reference's hand-rolled loop becomes one conv op. A fitted
whitener (x − μ)V is folded in algebraically: conv(X, Vᵀf) − (μVᵀf) per
filter, keeping everything a single fused computation.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from keystone_tpu.workflow import Transformer


class Convolver(Transformer):
    """filters: (num_filters, fh, fw, c) NHWC batch convolution, VALID."""

    def __init__(
        self,
        filters: jax.Array,
        stride: int = 1,
        whitener=None,
        compute_dtype: Optional[str] = None,
    ):
        filters = jnp.asarray(filters)
        self.num_filters, self.fh, self.fw, self.c = filters.shape
        if whitener is not None:
            # Fold ZCA: patch featurization is ((p − μ) M) fᵀ = p (M f) − μ M f.
            flat = filters.reshape(self.num_filters, -1)  # (nf, fh·fw·c)
            M = jnp.asarray(whitener.whitener)
            mu = jnp.asarray(whitener.mean)
            flat_w = flat @ M.T  # M is symmetric for ZCA; keep .T for clarity
            self.bias = -(mu @ M.T) @ flat.T  # (nf,)
            filters = flat_w.reshape(
                self.num_filters, self.fh, self.fw, self.c
            )
        else:
            self.bias = None
        self.filters = filters
        self.stride = stride
        # "bfloat16": feed images + filters to the MXU in bf16 with f32
        # accumulation — the conv throughput mode (outputs stay f32, so
        # rectify/pool downstream are untouched). Normalized + validated
        # here so "float32" means off everywhere and a bad dtype fails at
        # the constructor, not deep inside a fused trace.
        if compute_dtype is not None:
            dt = jnp.dtype(compute_dtype)
            compute_dtype = None if dt == jnp.float32 else str(dt)
        self.compute_dtype = compute_dtype

    def apply_batch(self, X):
        kwargs = {}
        filters = self.filters
        if self.compute_dtype is not None:
            dt = jnp.dtype(self.compute_dtype)
            X = X.astype(dt)
            filters = filters.astype(dt)
            kwargs["preferred_element_type"] = jnp.float32
        # NHWC × OHWI → NHWO
        out = lax.conv_general_dilated(
            X,
            filters,
            window_strides=(self.stride, self.stride),
            padding="VALID",
            dimension_numbers=("NHWC", "OHWI", "NHWC"),
            **kwargs,
        )
        if self.bias is not None:
            out = out + self.bias
        return out
