"""Spatial pooling and the symmetric rectifier.

Ref: src/main/scala/nodes/images/{Pooler,SymmetricRectifier}.scala — sum
pooling over a spatial grid; symmetric rectification doubles channels into
(x − α)⁺ and (−x − α)⁺ (SURVEY.md §2.5) [unverified].

TPU lowering: `lax.reduce_window` (pooling) and fused elementwise max/concat.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from keystone_tpu.workflow import Transformer


class SymmetricRectifier(Transformer):
    def __init__(self, alpha: float = 0.0, max_val: float = 0.0):
        self.alpha = alpha
        self.max_val = max_val

    def signature(self):
        return self.stable_signature(self.alpha, self.max_val)

    def apply_batch(self, X):
        pos = jnp.maximum(X - self.alpha, self.max_val)
        neg = jnp.maximum(-X - self.alpha, self.max_val)
        return jnp.concatenate([pos, neg], axis=-1)


class Pooler(Transformer):
    """Pool NHWC over (pool_size × pool_size) windows with `stride`.

    mode: "sum" (the reference's default for CIFAR features), "mean", "max".
    """

    def __init__(self, stride: int, pool_size: int, mode: str = "sum"):
        if mode not in ("sum", "mean", "max"):
            raise ValueError(f"unknown pooling mode {mode!r}")
        self.stride = stride
        self.pool_size = pool_size
        self.mode = mode

    def signature(self):
        return self.stable_signature(self.stride, self.pool_size, self.mode)

    def apply_batch(self, X):
        dims = (1, self.pool_size, self.pool_size, 1)
        strides = (1, self.stride, self.stride, 1)
        if self.mode == "max":
            return lax.reduce_window(
                X, -jnp.inf, lax.max, dims, strides, "VALID"
            )
        out = lax.reduce_window(X, 0.0, lax.add, dims, strides, "VALID")
        if self.mode == "mean":
            out = out / (self.pool_size * self.pool_size)
        return out
