"""Local color statistics (LCS) descriptors.

Ref: src/main/scala/nodes/images/LCSExtractor.scala — the ImageNet
pipeline's second descriptor channel: per keypoint, per 4×4 sub-cell, the
mean and standard deviation of each color channel → 96-dim descriptors
(4·4 cells × 3 channels × 2 statistics) (SURVEY.md §2.5, BASELINE.json)
[unverified].

TPU lowering: the per-cell sums are two reduce_window box filters (x and
x²) computed once per image, then gathered at the dense keypoint grid —
all jittable, same grid geometry as the SIFT extractor so the two branches
stay keypoint-aligned.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from keystone_tpu.workflow import Transformer

_CELLS = 4  # 4x4 sub-cells, matching the SIFT spatial grid


class LCSExtractor(Transformer):
    def __init__(self, step: int = 4, bin_size: int = 4, eps: float = 1e-8):
        self.step = step
        self.bin_size = bin_size
        self.eps = eps

    def signature(self):
        return self.stable_signature(self.step, self.bin_size, self.eps)

    def num_keypoints(self, h: int, w: int) -> int:
        span = _CELLS * self.bin_size
        nx = (w - span) // self.step + 1 if w >= span else 0
        ny = (h - span) // self.step + 1 if h >= span else 0
        return nx * ny

    def apply_batch(self, X):
        n, h, w, c = X.shape
        bs = self.bin_size
        span = _CELLS * bs
        if h < span or w < span:
            raise ValueError(
                f"image ({h}x{w}) smaller than the {span}px descriptor "
                f"support (bin_size={bs} x {_CELLS} cells)"
            )
        ny = (h - span) // self.step + 1
        nx = (w - span) // self.step + 1
        # Box-filter sums of x and x² over bin_size windows, stride 1.
        dims = (1, bs, bs, 1)
        ones = (1, 1, 1, 1)
        s1 = lax.reduce_window(X, 0.0, lax.add, dims, ones, "VALID")
        s2 = lax.reduce_window(X * X, 0.0, lax.add, dims, ones, "VALID")
        area = bs * bs
        # Cell top-left corners for every keypoint and sub-cell.
        ky = jnp.arange(ny) * self.step  # keypoint tops
        kx = jnp.arange(nx) * self.step
        cell = jnp.arange(_CELLS) * bs
        rows = (ky[:, None] + cell[None, :]).reshape(-1)  # (ny*4,)
        cols = (kx[:, None] + cell[None, :]).reshape(-1)  # (nx*4,)
        # Gather: (n, ny*4, nx*4, c)
        g1 = s1[:, rows][:, :, cols]
        g2 = s2[:, rows][:, :, cols]
        mean = g1 / area
        var = jnp.maximum(g2 / area - mean * mean, 0.0)
        std = jnp.sqrt(var + self.eps)
        stats = jnp.concatenate([mean, std], axis=-1)  # (n, ny*4, nx*4, 2c)
        # Regroup into per-keypoint descriptors: (n, ny, 4, nx, 4, 2c).
        stats = stats.reshape(n, ny, _CELLS, nx, _CELLS, 2 * c)
        stats = jnp.moveaxis(stats, 3, 2)  # (n, ny, nx, 4, 4, 2c)
        return stats.reshape(n, ny * nx, _CELLS * _CELLS * 2 * c)
