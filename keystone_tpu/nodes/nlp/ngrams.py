"""N-gram featurizer over token sequences.

Ref: src/main/scala/nodes/nlp/NGramsFeaturizer.scala — emits all n-grams
for n in [min_n, max_n] (SURVEY.md §2.7) [unverified].
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from keystone_tpu.workflow import Transformer


class NGramsFeaturizer(Transformer):
    jittable = False

    def __init__(self, min_n: int = 1, max_n: int = 2, joiner: str = " "):
        if min_n < 1 or max_n < min_n:
            raise ValueError(f"bad n-gram range [{min_n}, {max_n}]")
        self.min_n = min_n
        self.max_n = max_n
        self.joiner = joiner

    def signature(self):
        return self.stable_signature(self.min_n, self.max_n, self.joiner)

    def apply(self, tokens: Sequence[str]) -> List[str]:
        out: List[str] = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(tokens) - n + 1):
                out.append(self.joiner.join(tokens[i : i + n]))
        return out
