from keystone_tpu.nodes.nlp.tokenize import LowerCase, Tokenizer, Trim
from keystone_tpu.nodes.nlp.ngrams import NGramsFeaturizer
from keystone_tpu.nodes.nlp.term_frequency import TermFrequency
from keystone_tpu.nodes.nlp.encoders import (
    CommonSparseFeatures,
    WordFrequencyEncoder,
)

__all__ = [
    "Trim",
    "LowerCase",
    "Tokenizer",
    "NGramsFeaturizer",
    "TermFrequency",
    "CommonSparseFeatures",
    "WordFrequencyEncoder",
]
