"""Vocabulary encoders: top-K term dictionary → dense feature vectors.

Ref: src/main/scala/nodes/util/CommonSparseFeatures.scala and
nodes/nlp/WordFrequencyEncoder.scala — keep the K most frequent terms and
encode documents against that dictionary (SURVEY.md §2.7/§2.8)
[unverified].

TPU note: the reference emits Spark sparse vectors; here encoding emits
dense (batch, K) arrays at small K — what the MXU-backed classifiers want —
and switches to a host-side CSR ``SparseBatch`` once K crosses
``config.text_sparse_threshold`` (``sparse="auto"``), so vocab ≫ 10k never
materializes an (n, vocab) dense array; downstream consumers (naive Bayes,
the block solvers) densify per column block on their way to the device.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Mapping, Sequence, Union

import numpy as np

from keystone_tpu.config import config
from keystone_tpu.utils.sparse import SparseBatch
from keystone_tpu.workflow import Estimator, Transformer


def _want_sparse(sparse: Union[bool, str], dim: int) -> bool:
    if sparse == "auto":
        return dim >= config.text_sparse_threshold
    return bool(sparse)


class SparseFeatureVectorizer(Transformer):
    """Encodes (term → weight) maps against a fixed term index."""

    jittable = False

    def __init__(self, index: Mapping[str, int], sparse: Union[bool, str] = "auto"):
        self.index = dict(index)
        self.dim = len(self.index)
        self.sparse = sparse

    def apply_batch(self, docs: Sequence[Mapping[str, float]]):
        if _want_sparse(self.sparse, self.dim):
            return SparseBatch.from_term_maps(docs, self.index, self.dim)
        out = np.zeros((len(docs), self.dim), dtype=config.default_dtype)
        index = self.index
        for i, doc in enumerate(docs):
            for term, weight in doc.items():
                j = index.get(term)
                if j is not None:
                    out[i, j] = weight
        return out

    @property
    def vocabulary(self) -> List[str]:
        inv = [""] * self.dim
        for term, j in self.index.items():
            inv[j] = term
        return inv


class CountVectorizer(SparseFeatureVectorizer):
    """Encodes token lists as count vectors against a fixed index."""

    def apply_batch(self, docs: Sequence[Sequence[str]]):
        if _want_sparse(self.sparse, self.dim):
            return SparseBatch.from_counts(docs, self.index, self.dim)
        out = np.zeros((len(docs), self.dim), dtype=config.default_dtype)
        index = self.index
        for i, tokens in enumerate(docs):
            for t in tokens:
                j = index.get(t)
                if j is not None:
                    out[i, j] += 1.0
        return out


class CommonSparseFeatures(Estimator):
    """Fit: keep the `num_features` terms appearing in the most documents."""

    def __init__(self, num_features: int, sparse: Union[bool, str] = "auto"):
        self.num_features = num_features
        self.sparse = sparse

    def fit(self, docs: Sequence[Mapping[str, float]]) -> SparseFeatureVectorizer:
        doc_freq: Counter = Counter()
        for doc in docs:
            doc_freq.update(doc.keys())
        top = [t for t, _c in doc_freq.most_common(self.num_features)]
        return SparseFeatureVectorizer(
            {t: i for i, t in enumerate(top)}, sparse=self.sparse
        )


class WordFrequencyEncoder(Estimator):
    """Fit over token lists: most frequent words → index; encodes documents
    as count vectors."""

    def __init__(self, num_words: int, sparse: Union[bool, str] = "auto"):
        self.num_words = num_words
        self.sparse = sparse

    def fit(self, token_docs: Sequence[Sequence[str]]) -> CountVectorizer:
        freq: Counter = Counter()
        for tokens in token_docs:
            freq.update(tokens)
        top = [w for w, _c in freq.most_common(self.num_words)]
        return CountVectorizer(
            {w: i for i, w in enumerate(top)}, sparse=self.sparse
        )
