"""Term-frequency weighting over token multisets.

Ref: src/main/scala/nodes/nlp/TermFrequency.scala — maps each document's
terms to (term, weight) with a pluggable weighting (identity or log)
(SURVEY.md §2.7) [unverified].
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Callable, Dict, Sequence

from keystone_tpu.workflow import Transformer


class TermFrequency(Transformer):
    jittable = False

    def __init__(self, fn: str | Callable[[float], float] = "identity"):
        if fn == "identity":
            self.fn: Callable[[float], float] = lambda c: c
        elif fn == "log":
            self.fn = lambda c: math.log(c + 1.0)
        elif callable(fn):
            self.fn = fn
        else:
            raise ValueError(f"unknown weighting {fn!r}")

    def apply(self, tokens: Sequence[str]) -> Dict[str, float]:
        return {t: self.fn(c) for t, c in Counter(tokens).items()}
