"""Term-frequency weighting over token multisets.

Ref: src/main/scala/nodes/nlp/TermFrequency.scala — maps each document's
terms to (term, weight) with a pluggable weighting (identity or log)
(SURVEY.md §2.7) [unverified].
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Callable, Dict, Sequence

from keystone_tpu.workflow import Transformer


def _identity(c: float) -> float:
    return c


def _log1p(c: float) -> float:
    return math.log(c + 1.0)


# Named module-level functions (not lambdas): fitted text pipelines pickle
# through save_pipeline, and the name doubles as a content-stable signature.
_WEIGHTINGS: Dict[str, Callable[[float], float]] = {
    "identity": _identity,
    "log": _log1p,
}


class TermFrequency(Transformer):
    jittable = False

    def __init__(self, fn: str | Callable[[float], float] = "identity"):
        if isinstance(fn, str):
            if fn not in _WEIGHTINGS:
                raise ValueError(f"unknown weighting {fn!r}")
            self.fn = _WEIGHTINGS[fn]
            self._sig = self.stable_signature(fn)
        elif callable(fn):
            self.fn = fn  # custom callables keep identity-based hashing
        else:
            raise ValueError(f"unknown weighting {fn!r}")

    def apply(self, tokens: Sequence[str]) -> Dict[str, float]:
        return {t: self.fn(c) for t, c in Counter(tokens).items()}
