"""Text cleanup and tokenization nodes (host-side: irregular string work is
CPU work; the TPU sees only the encoded vectors downstream).

Ref: src/main/scala/nodes/nlp/{Trim,LowerCase,Tokenizer}.scala
(SURVEY.md §2.7) [unverified].
"""

from __future__ import annotations

import re
from typing import List

from keystone_tpu.workflow import Transformer


class Trim(Transformer):
    jittable = False

    def signature(self):
        # Parameterless + deterministic: content-stable so text prefixes
        # through it keep a persistable digest (cross-process fit cache).
        return self.stable_signature()

    def apply(self, x: str) -> str:
        return x.strip()


class LowerCase(Transformer):
    jittable = False

    def signature(self):
        return self.stable_signature()

    def apply(self, x: str) -> str:
        return x.lower()


class Tokenizer(Transformer):
    """Split on a regex (default: runs of non-word characters)."""

    jittable = False

    def __init__(self, pattern: str = r"[^\w']+"):
        self.pattern = re.compile(pattern)

    def signature(self):
        return self.stable_signature(self.pattern.pattern)

    def apply(self, x: str) -> List[str]:
        return [t for t in self.pattern.split(x) if t]
