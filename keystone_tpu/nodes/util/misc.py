"""Small utility nodes.

Ref: src/main/scala/nodes/util/{Identity,FloatToDouble,VectorSplitter,
VectorCombiner}.scala [unverified]. `FloatToDouble` generalizes to `Cast`
(on TPU the interesting casts are bf16 ↔ f32).
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from keystone_tpu.workflow import Transformer


class Identity(Transformer):
    def signature(self):
        return self.stable_signature()

    def apply_batch(self, X):
        return X


class Cacher(Transformer):
    """API-parity alias for the reference's Cacher node: composing
    ``pipeline.and_then(Cacher())`` persists the value in the session cache
    exactly like ``pipeline.cache()`` (Ref: workflow/Cacher.scala
    [unverified])."""

    jittable = False

    def to_pipeline(self):
        from keystone_tpu.workflow.cache import CacheOperator
        from keystone_tpu.workflow.graph import Graph, fresh_source_id
        from keystone_tpu.workflow.pipeline import Pipeline

        source = fresh_source_id()
        graph, nid = Graph().add(CacheOperator(), [source])
        return Pipeline(graph, source, nid)

    def apply_batch(self, X):  # direct eager use: identity
        return X


class Cast(Transformer):
    def __init__(self, dtype):
        self.dtype = jnp.dtype(dtype)

    def signature(self):
        return ("cast", str(self.dtype))

    def apply_batch(self, X):
        return jnp.asarray(X).astype(self.dtype)


class VectorSplitter(Transformer):
    """Split the feature axis into fixed-size blocks (the bridge into the
    block solvers; Ref: nodes/util/VectorSplitter.scala [unverified]).

    Returns a list of arrays — host-level structure, so not jittable.
    """

    jittable = False

    def __init__(self, block_size: int):
        self.block_size = block_size

    def apply_batch(self, X) -> List:
        d = X.shape[-1]
        return [
            X[..., s : min(s + self.block_size, d)]
            for s in range(0, d, self.block_size)
        ]


class VectorCombiner(Transformer):
    """Concatenate a list of feature blocks back together."""

    jittable = False

    def apply_batch(self, blocks):
        return jnp.concatenate(blocks, axis=-1)


class Densify(Transformer):
    """(index → value) mappings → dense rows of a fixed dimension.

    Ref: nodes/util/Densify.scala [unverified]. On TPU every downstream
    consumer wants dense batches; this is the boundary node.
    """

    jittable = False

    def __init__(self, dim: int):
        self.dim = dim

    def apply_batch(self, docs):
        import numpy as np

        from keystone_tpu.config import config

        out = np.zeros((len(docs), self.dim), dtype=config.default_dtype)
        for i, doc in enumerate(docs):
            items = doc.items() if hasattr(doc, "items") else doc
            for j, v in items:
                j = int(j)
                if not 0 <= j < self.dim:
                    raise ValueError(
                        f"feature index {j} out of range [0, {self.dim})"
                    )
                out[i, j] = v
        return out


class Sparsify(Transformer):
    """Dense rows → (index → value) dicts of the nonzero entries
    (Ref: nodes/util/SparseFeatureVectorizer direction [unverified])."""

    jittable = False

    def apply_batch(self, X):
        import numpy as np

        X = np.asarray(X)
        return [
            {int(j): float(X[i, j]) for j in np.flatnonzero(X[i])}
            for i in range(X.shape[0])
        ]
