from keystone_tpu.nodes.util.labels import ClassLabelIndicators
from keystone_tpu.nodes.util.classifiers import MaxClassifier, TopKClassifier
from keystone_tpu.nodes.util.misc import (
    Cacher,
    Cast,
    Densify,
    Identity,
    Sparsify,
    VectorCombiner,
    VectorSplitter,
)

__all__ = [
    "ClassLabelIndicators",
    "MaxClassifier",
    "TopKClassifier",
    "Cast",
    "Cacher",
    "Identity",
    "VectorSplitter",
    "VectorCombiner",
    "Densify",
    "Sparsify",
]
