from keystone_tpu.nodes.util.labels import ClassLabelIndicators
from keystone_tpu.nodes.util.classifiers import MaxClassifier, TopKClassifier
from keystone_tpu.nodes.util.misc import Cast, Identity, VectorCombiner, VectorSplitter

__all__ = [
    "ClassLabelIndicators",
    "MaxClassifier",
    "TopKClassifier",
    "Cast",
    "Identity",
    "VectorSplitter",
    "VectorCombiner",
]
