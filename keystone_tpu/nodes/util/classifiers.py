"""Prediction post-processing nodes.

Ref: src/main/scala/nodes/util/{MaxClassifier,TopKClassifier}.scala —
argmax / top-k over the score vector [unverified].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from keystone_tpu.workflow import Transformer


class MaxClassifier(Transformer):
    def signature(self):
        return self.stable_signature()

    def apply_batch(self, scores):
        return jnp.argmax(scores, axis=-1).astype(jnp.int32)


class TopKClassifier(Transformer):
    """Indices of the k largest scores, best first."""

    def __init__(self, k: int):
        self.k = k

    def signature(self):
        return self.stable_signature(self.k)

    def apply_batch(self, scores):
        _, idx = jax.lax.top_k(scores, self.k)
        return idx.astype(jnp.int32)
