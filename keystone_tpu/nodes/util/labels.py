"""Label encoding nodes.

Ref: src/main/scala/nodes/util/ClassLabelIndicators.scala —
`ClassLabelIndicatorsFromIntLabels`: int label → dense ±1 indicator vector
(+1 at the class index, −1 elsewhere), the regression target encoding for
the least-squares classifiers [unverified].
"""

from __future__ import annotations

import jax.numpy as jnp

from keystone_tpu.config import config
from keystone_tpu.workflow import Transformer


class ClassLabelIndicators(Transformer):
    def __init__(self, num_classes: int):
        self.num_classes = num_classes

    def signature(self):
        return self.stable_signature(self.num_classes)

    def apply_batch(self, y):
        y = jnp.asarray(y).astype(jnp.int32)
        onehot = jnp.zeros(
            (y.shape[0], self.num_classes), dtype=config.default_dtype
        )
        onehot = onehot.at[jnp.arange(y.shape[0]), y].set(1.0)
        return 2.0 * onehot - 1.0
