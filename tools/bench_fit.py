"""Stage-parallel executor bench: serial vs parallel fit wall clock.

The ISSUE-10 tentpole claim, measured: a two-branch host-featurize →
solve pipeline (the ImageNet SIFT|LCS shape — two independent
non-jittable featurizer branches gathered into one least-squares fit)
is fitted twice, once under the legacy serial walk
(``config.exec_workers = 0``) and once under the dependency-counting
ready-set scheduler (``= N`` workers), and the wall clocks are compared.

The host featurizer is deliberately GIL-friendly single-threaded numpy
(FFT + elementwise chains, no BLAS that might multi-thread underneath):
the serial walk runs the two branches back to back on one core, the
parallel walk overlaps them on the worker pool — exactly the win the
scheduler exists for. Work is a FIXED iteration count, so outputs are
deterministic and the bit-identity gate is exact.

Gates:

- outputs bit-identical: the fitted pipeline applied to held-out rows
  must produce byte-equal predictions under both walks (hard, always);
- wall-clock speedup >= 1.3x (hard only when the fingerprint shows >= 2
  host cores AND >= 2 workers — on a 1-core container the pool
  time-slices one core, so the gate there is merely "no worse than
  0.75x", the PR-5 replica-bench precedent).

The result row APPENDS to ``--out`` (BENCH_fit.json) as fingerprinted
JSONL history — ``make bench-watch`` fits noise bands over prior rows
and flags a wall-clock/speedup regression in any later run.

Usage: python tools/bench_fit.py [--branches 2] [--workers 4]
           [--reps 3] [--quick] [--out BENCH_fit.json]
Prints one JSON line; exit 1 on a failed hard gate.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from keystone_tpu.workflow.pipeline import Pipeline, Transformer  # noqa: E402


class HostFFTFeaturizer(Transformer):
    """A deterministic host-bound featurizer branch (the SIFT/LCS
    stand-in): ``iters`` rounds of rFFT -> spectral filter -> irFFT ->
    tanh. Pure single-threaded numpy that releases the GIL, so two
    branches genuinely overlap on the worker pool; a fixed iteration
    count keeps the output (and thus the bit-identity gate) exact."""

    jittable = False

    def __init__(self, seed: int, iters: int):
        self.seed = int(seed)
        self.iters = int(iters)

    def signature(self):
        return self.stable_signature(self.seed, self.iters)

    def apply_batch(self, X):
        Y = np.asarray(X, dtype=np.float32)
        rng = np.random.default_rng(self.seed)
        filt = (1.0 + rng.uniform(size=Y.shape[1] // 2 + 1)).astype(
            np.complex64
        )
        for _ in range(self.iters):
            spec = np.fft.rfft(Y, axis=1) * filt
            Y = np.tanh(
                Y + np.fft.irfft(spec, n=Y.shape[1], axis=1).astype(
                    np.float32
                )
            )
        return Y


def build_fit_pipeline(X, y, branches: int, work_iters: int) -> Pipeline:
    """``branches`` independent host featurizers gathered into one
    block-least-squares solve — the two-branch ImageNet featurizer
    shape at bench scale."""
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator

    fronts = [
        HostFFTFeaturizer(seed=i + 1, iters=work_iters).to_pipeline()
        for i in range(branches)
    ]
    feat = fronts[0] if branches == 1 else Pipeline.gather(fronts)
    return feat.and_then(
        BlockLeastSquaresEstimator(
            block_size=max(32, X.shape[1]), num_iters=1, lam=1e-3
        ),
        X,
        y,
    )


def _timed_fit(X, y, X_test, branches, work_iters, workers):
    """One cold fit under ``workers`` executor threads: fresh session
    caches (no fit-cache hit can short-circuit the measured walk),
    returns (wall seconds, held-out predictions)."""
    from keystone_tpu.config import config
    from keystone_tpu.workflow.executor import PipelineEnv

    PipelineEnv.reset()
    prev = config.exec_workers
    config.exec_workers = workers
    try:
        pipe = build_fit_pipeline(X, y, branches, work_iters)
        t0 = time.perf_counter()
        fitted = pipe.fit()
        wall = time.perf_counter() - t0
        preds = np.asarray(fitted.apply(X_test).get())
    finally:
        config.exec_workers = prev
        PipelineEnv.reset()
    return wall, preds


def run_bench(args) -> dict:
    rng = np.random.default_rng(0)
    n, d, k = args.rows, args.dim, args.classes
    X = rng.normal(size=(n, d)).astype(np.float32)
    W_true = rng.normal(size=(d, k)).astype(np.float32)
    y = (X @ W_true + 0.01 * rng.normal(size=(n, k))).astype(np.float32)
    X_test = rng.normal(size=(64, d)).astype(np.float32)

    # Untimed warmup: the first fit in the process pays the solver's XLA
    # compiles (jit caches are process-wide, not session-scoped); without
    # this the serial rep eats the compile cost and the "speedup" lies.
    _timed_fit(X, y, X_test, args.branches, args.work_iters, 0)

    serial_walls, parallel_walls = [], []
    serial_preds = parallel_preds = None
    for _ in range(args.reps):
        wall, serial_preds = _timed_fit(
            X, y, X_test, args.branches, args.work_iters, 0
        )
        serial_walls.append(wall)
        wall, parallel_preds = _timed_fit(
            X, y, X_test, args.branches, args.work_iters, args.workers
        )
        parallel_walls.append(wall)

    serial_s = statistics.median(serial_walls)
    parallel_s = statistics.median(parallel_walls)
    speedup = serial_s / parallel_s if parallel_s > 0 else float("inf")
    bit_identical = bool(
        serial_preds.shape == parallel_preds.shape
        and np.array_equal(serial_preds, parallel_preds)
    )

    import jax

    from keystone_tpu.utils.metrics import environment_fingerprint

    cores = os.cpu_count() or 1
    # One core cannot run two host branches at once: the 1.3x gate is
    # hard only where the hardware can express the overlap (the PR-5
    # replica-bench precedent); a 1-core container must merely not get
    # meaningfully SLOWER from scheduler overhead.
    gate_is_hard = cores >= 2 and args.workers >= 2
    speedup_gate = speedup >= (1.3 if gate_is_hard else 0.75)
    row = {
        "metric": "fit_parallel_walk",
        "value": round(speedup, 3),
        "unit": "x speedup (serial fit wall / parallel fit wall)",
        "backend": jax.default_backend(),
        "host_cores": cores,
        "env": environment_fingerprint(),
        "detail": {
            "branches": args.branches,
            "exec_workers": args.workers,
            "reps": args.reps,
            "work_iters": args.work_iters,
            "rows": n,
            "dim": d,
            "classes": k,
            "serial_wall_s": round(serial_s, 4),
            "parallel_wall_s": round(parallel_s, 4),
            "bit_identical": bit_identical,
            "speedup_gate": speedup_gate,
            "speedup_gate_is_hard": gate_is_hard,
        },
    }
    # --quick is harness validation: the tiny problem is all scheduler
    # overhead, so only bit-identity is judged there.
    row["ok"] = bool(
        bit_identical and (speedup_gate or getattr(args, "quick", False))
    )
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="serial-vs-parallel executor walk fit bench"
    )
    ap.add_argument("--branches", type=int, default=2,
                    help="independent host featurizer branches")
    ap.add_argument("--workers", type=int, default=4,
                    help="KEYSTONE_EXEC_WORKERS for the parallel walk")
    ap.add_argument("--reps", type=int, default=3,
                    help="cold fits per mode; the median wall is reported")
    ap.add_argument("--rows", type=int, default=384)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--classes", type=int, default=8)
    ap.add_argument("--work-iters", type=int, default=60,
                    help="FFT/tanh rounds per host branch (fixed count: "
                         "deterministic outputs)")
    ap.add_argument("--quick", action="store_true",
                    help="tiny problem, 1 rep — harness validation only, "
                         "no row is written and gates are soft")
    ap.add_argument("--out", default=None,
                    help="append the fingerprinted JSONL row here")
    args = ap.parse_args(argv)

    if args.quick:
        args.rows, args.dim, args.classes = 96, 64, 4
        args.work_iters, args.reps = 8, 1

    row = run_bench(args)
    print(json.dumps(row), flush=True)

    if args.out and not args.quick:
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")

    if not row["detail"]["bit_identical"]:
        print("GATE FAILED: parallel fit outputs differ from serial",
              file=sys.stderr)
        return 1
    if not row["detail"]["speedup_gate"] and not args.quick:
        bound = 1.3 if row["detail"]["speedup_gate_is_hard"] else 0.75
        print(
            f"GATE FAILED: speedup {row['value']}x < {bound}x "
            f"({'hard' if row['detail']['speedup_gate_is_hard'] else 'soft'}"
            f" gate at {row['host_cores']} cores)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
