"""Ring (model-parallel) vs data-parallel BCD timing — when does the
d-sharded ring actually win?

``parallelism="model"`` (linalg/ring_bcd.py) shards the FEATURE axis and
rings n×k/P residual chunks over ppermute; ``parallelism="data"`` shards
rows and psums b×b grams. The docstring claim — ring wins when d dwarfs
n·k — had no timing behind it (VERDICT r4 weak #8). This tool times both
solvers on the same problem at a d≫n·k shape and a d≈n·k control shape,
on whatever backend is live:

- CPU 8-device mesh: the distributed SCHEDULE sanity check (collectives
  are emulated, so ratios bound program/schedule overhead, not ICI).
- TPU (one chip here): per-step program efficiency of the two lowerings
  at identical shapes; the ring's comm advantage needs a real multi-chip
  mesh, which this environment does not expose — recorded as such.

Prints ONE JSON line (checkride `ring_vs_dp` step).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _solve_dp(A, B, block, iters, lam):
    import jax

    from keystone_tpu.linalg import RowMatrix, block_coordinate_descent

    Ma, Mb = RowMatrix.from_array(A), RowMatrix.from_array(B)
    W_blocks, _ = block_coordinate_descent(
        Ma, Mb, block_size=block, num_iters=iters, lam=lam, cache_grams=True
    )
    jax.block_until_ready(W_blocks[-1])
    return np.concatenate([np.asarray(w) for w in W_blocks], axis=0)


def _solve_ring(A, B, iters, lam):
    import jax

    from keystone_tpu.linalg import block_coordinate_descent_ring

    W = block_coordinate_descent_ring(A, B, num_iters=iters, lam=lam)
    jax.block_until_ready(W)
    return np.asarray(W)


def _timed(fn, reps):
    fn()  # compile + warm-up outside the timed region
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    dt = (time.perf_counter() - t0) / reps
    return out, dt


def measure(n, d, k, iters, lam, reps):
    import jax

    rng = np.random.default_rng(0)
    A = (rng.normal(size=(n, d)) / np.sqrt(d)).astype(np.float32)
    W_true = rng.normal(size=(d, k)).astype(np.float32)
    B = A @ W_true
    nshards = len(jax.devices())
    # d % nshards validated in main() (naming the offending flag).
    block = d // nshards  # DP uses the ring's per-chip block for parity

    W_dp, t_dp = _timed(lambda: _solve_dp(A, B, block, iters, lam), reps)
    W_ring, t_ring = _timed(lambda: _solve_ring(A, B, iters, lam), reps)

    bnorm = float(np.linalg.norm(B))
    return {
        "n": n, "d": d, "k": k, "iters": iters,
        "nk_over_d": round(n * k / d, 2),
        "block": block,
        "dp_seconds": round(t_dp, 4),
        "ring_seconds": round(t_ring, 4),
        "ring_speedup": round(t_dp / t_ring, 3),
        "dp_relative_residual": round(
            float(np.linalg.norm(A @ W_dp - B)) / bnorm, 5
        ),
        "ring_relative_residual": round(
            float(np.linalg.norm(A @ W_ring - B)) / bnorm, 5
        ),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=2048)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--d-wide", type=int, default=65536,
                    help="the d>>n*k shape (ring's home turf)")
    ap.add_argument("--d-control", type=int, default=8192,
                    help="a d~n*k control shape")
    ap.add_argument("--iters", type=int, default=2)
    ap.add_argument("--lam", type=float, default=1e-3)
    ap.add_argument("--reps", type=int, default=2)
    args = ap.parse_args()

    from keystone_tpu.utils.platform import ensure_live_backend

    backend = ensure_live_backend()
    import jax

    # Validate up front, naming the offending flag — a non-divisible d
    # otherwise surfaces deep in the solvers as an opaque shape error.
    ndev = len(jax.devices())
    for flag, d in (("--d-control", args.d_control), ("--d-wide", args.d_wide)):
        if d % ndev != 0:
            sys.exit(
                f"error: {flag}={d} is not divisible by the device count "
                f"({ndev}); the ring solver shards d per chip and the DP "
                "run reuses d // n_devices as its block size — pick a "
                f"multiple of {ndev}"
            )

    rows = [
        measure(args.n, d, args.k, args.iters, args.lam, args.reps)
        for d in (args.d_control, args.d_wide)
    ]
    print(json.dumps({
        "metric": "ring_vs_dp_bcd",
        "backend": backend,
        "n_devices": len(jax.devices()),
        "single_chip_note": (
            "ring comm advantage needs >1 chip; this row compares program "
            "schedules only" if len(jax.devices()) == 1 else None
        ),
        "rows": rows,
    }))


if __name__ == "__main__":
    main()
