"""Sentinel loop: relaunch the resumable checkride whenever the chip returns.

The axon relay has died mid-session in all three rounds, and each live
window arrives unannounced. This loop probes the TPU on a fixed cadence
(short-timeout subprocess, no backend state left behind) and, the moment a
probe succeeds, runs `tools/checkride.py` — which resumes from the state
dir, keeps every checkpointed TPU row, and re-runs only the steps whose
stored result is a CPU fallback. Exits when TPU_REPORT.json reaches
``complete_on_tpu`` (or after --max-hours).

Usage: nohup python tools/checkride_sentinel.py >> sentinel.log 2>&1 &
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _report_complete(report_path: str) -> bool:
    try:
        with open(report_path) as f:
            return bool(json.load(f).get("complete_on_tpu"))
    except (OSError, ValueError):
        return False


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=1500.0,
                    help="seconds between probes (default 25 min)")
    ap.add_argument("--probe-timeout", type=float, default=120.0)
    ap.add_argument("--max-hours", type=float, default=10.0)
    ap.add_argument("--report", default=os.path.join(REPO, "TPU_REPORT.json"))
    args = ap.parse_args()

    deadline = time.time() + args.max_hours * 3600.0
    while time.time() < deadline:
        if _report_complete(args.report):
            print("sentinel: report complete_on_tpu; done", flush=True)
            return
        from keystone_tpu.utils.platform import probe_backend

        info = probe_backend(timeout=args.probe_timeout)
        print(f"sentinel: probe={info}", flush=True)
        if info is not None and info.get("platform") == "tpu":
            remaining = deadline - time.time()
            if remaining < 300.0:
                break  # not enough window left to do useful ride work
            # Live window — spend it on the ride, not on sleeping. Bound by
            # the remaining budget; a killed ride keeps checkpointed steps.
            try:
                rc = subprocess.call(
                    [
                        sys.executable,
                        os.path.join(REPO, "tools", "checkride.py"),
                        "--report",
                        args.report,
                    ],
                    timeout=remaining,
                )
            except subprocess.TimeoutExpired:
                rc = "timeout"
            print(f"sentinel: checkride rc={rc}", flush=True)
            if _report_complete(args.report):
                print("sentinel: report complete_on_tpu; done", flush=True)
                return
        time.sleep(args.interval)
    print("sentinel: max-hours reached", flush=True)


if __name__ == "__main__":
    main()
