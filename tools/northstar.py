"""North-star projection: ImageNet SIFT+LCS+FV+BWLS on a v5e-64, from
measured single-chip rates.

BASELINE.md's authoritative target is "ImageNet FV+BlockLS end-to-end
<= 10 min on TPU v5e-64, >= 10x the published 16-node EC2 baseline". No
64-chip slice exists in this environment, so this tool does the honest
next-best thing: a stage-by-stage bottleneck model whose inputs are the
checkride's MEASURED single-chip numbers (TPU_REPORT.json) wherever they
exist, with every remaining constant printed as a labelled assumption.
Stages with no silicon measurement are reported as REQUIRED rates (what
the hosts/chips must sustain for the 10-min budget), not as claims.

This is a PROJECTION, not a measurement — the output says so. It
self-upgrades: re-run after the sentinel captures more TPU steps and the
"assumed" rows flip to "measured(tpu)".

Workload constants follow the reference pipeline (SURVEY.md §2.11
ImageNetSiftLcsFV [unverified]): N=1.28M train images, two descriptor
branches (SIFT + LCS) -> PCA(64) -> GMM(k=256) Fisher vectors -> 64k-dim
features -> BlockWeightedLeastSquares(k=1000).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # bcd_flops — the same FLOP model the measured TFLOPS uses

N_IMAGES = 1_281_167
K_CLASSES = 1000
D_FEATURES = 65_536
SOLVER_EPOCHS = 3
SOLVER_BLOCK = 8192  # matches bench.SCALE["tpu-imagenet"] (auto-sized r3 sweep)
CHIPS = 64
# Data-parallel BCD psums one b×b gram per block per epoch over ICI; on a
# 64-chip torus that collective overlaps poorly only at small n/chip.
# 0.8 is a stated assumption, not a measurement.
SCALING_EFFICIENCY = 0.8
DESCRIPTORS_PER_IMAGE = 2048  # dense-SIFT grid at 256px, step 4 (assumed)


def _report_steps() -> dict:
    try:
        with open(os.path.join(REPO, "TPU_REPORT.json")) as f:
            return json.load(f).get("steps", {})
    except (OSError, ValueError):
        return {}


K_GMM = 256  # GMM components per branch (2 branches x 2*64*256 = 64k dims)


def _tpu(steps: dict, name: str):
    rec = steps.get(name)
    if (
        rec
        and rec.get("backend") == "tpu"
        and rec.get("ok")
        and not rec.get("quick_scale")  # toy-scale rides are not evidence
    ):
        return rec
    return None


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget-min", type=float, default=10.0)
    args = ap.parse_args()
    steps = _report_steps()
    try:
        with open(os.path.join(REPO, "HOSTBENCH.json")) as f:
            hb = json.load(f)
        if not float(hb.get("both_branches_img_per_sec") or 0) > 0:
            hb = None
    except (OSError, ValueError, TypeError):
        hb = None
    # Descriptor count: measured at the reference geometry when the host
    # bench ran; the 2048 constant otherwise.
    desc_per_img = (
        int(hb["sift_desc_per_img"]) if hb and hb.get("sift_desc_per_img")
        else DESCRIPTORS_PER_IMAGE
    )
    desc_basis = "measured" if hb else "assumed"
    rows = []

    # --- Solver: measured TFLOPS/chip × 64 chips × stated efficiency ----
    solver_flops = bench.bcd_flops(
        N_IMAGES, D_FEATURES, K_CLASSES, SOLVER_BLOCK, SOLVER_EPOCHS
    )
    # Prefer the AT-SHAPE measurement (bench_imagenet: d=65536, k=1000,
    # block=8192 on silicon) — its rate needs no transfer assumption. The
    # k=16 headline rows are the fallback, labelled as the rescale they are.
    shaped = _tpu(steps, "bench_imagenet")
    b = shaped or _tpu(steps, "bench_bf16") or _tpu(steps, "bench_f32")
    if b:
        tflops = b["tflops_per_chip"]
        dtype = b["bench_line"]["detail"]["dtype"]
        solver_s = solver_flops / (tflops * 1e12 * CHIPS * SCALING_EFFICIENCY)
        rate_basis = (
            "measured(tpu) AT ImageNet shape (d=65536, k=1000)"
            if shaped
            else "measured(tpu) at k=16 — RESCALED by FLOPs, assumes the "
            "rate transfers to k=1000"
        )
        rows.append(
            {
                "stage": f"BWLS solve (d=64k, k=1000, {SOLVER_EPOCHS} epochs)",
                "minutes": round(solver_s / 60, 2),
                "basis": f"{rate_basis}: {tflops} TFLOPS/chip ({dtype}) "
                f"x {CHIPS} chips x {SCALING_EFFICIENCY} eff (assumed)",
            }
        )
    else:
        rows.append(
            {
                "stage": "BWLS solve",
                "minutes": None,
                "basis": "awaiting silicon (run make tpu-checkride)",
            }
        )

    # --- Fisher-vector encode on chip (both branches) -------------------
    fv = _tpu(steps, "pallas_fv")
    if fv:
        per_batch = min(
            t for t in (fv.get("pallas_s"), fv.get("xla_s")) if t
        )
        bsz = fv["config"]["batch"]
        m = fv["config"]["m"]
        k_meas = fv["config"]["k"]
        # Rescale the measured batch to the ImageNet shape: descriptor
        # count AND GMM component count (FV cost is linear in both), then
        # double for the two branches.
        per_img = (
            per_batch / bsz * (desc_per_img / m) * (K_GMM / k_meas) * 2
        )
        fv_s = N_IMAGES * per_img / CHIPS
        rows.append(
            {
                "stage": "FV encode (SIFT+LCS branches)",
                "minutes": round(fv_s / 60, 2),
                "basis": f"measured(tpu) {per_batch:.4f}s per {bsz}x{m} batch, "
                f"{desc_per_img} desc/img ({desc_basis}) x {CHIPS} chips",
            }
        )
    else:
        rows.append(
            {
                "stage": "FV encode",
                "minutes": None,
                "basis": "awaiting silicon (pallas_fv step not yet on tpu)",
            }
        )

    # --- Sampled fits (PCA + GMM EM): negligible, shown with arithmetic --
    # PCA(64) on ~1M sampled descriptors and 25 EM iterations of a
    # k=256/d=64 GMM are ~2e12 matmul FLOPs per branch — sub-second at
    # even a tenth of the measured solver rate; listed so the stage
    # accounting is complete, not because it moves the total.
    rows.append(
        {
            "stage": "PCA + GMM fits (sampled)",
            "minutes": 0.1,
            "basis": "bounded: ~4e12 FLOPs total (2 branches) ≪ 1 chip-second"
            "; generous 0.1 min allowance",
        }
    )

    # --- Host-side decode + SIFT/LCS: required rate vs measured rate ----
    # Chip-stage total BEFORE the host rows append — the host rows carry
    # the remaining budget, not chip time.
    chip_minutes = round(sum(r["minutes"] or 0 for r in rows), 2)
    budget_s = args.budget_min * 60
    spent = sum(r["minutes"] or 0 for r in rows) * 60
    remaining = max(budget_s - spent, 0.0)
    req = N_IMAGES / remaining if remaining > 0 else float("inf")
    DECODE_PER_CORE = 273.0  # img/s/core, native pool 512->256px (NOTES_r3 §7)
    basis = (
        f"REQUIREMENT: fleet must sustain {req:,.0f} img/s aggregate in "
        "the remaining budget"
    )
    if hb is not None:
        both = float(hb["both_branches_img_per_sec"])
        per_core = 1.0 / (1.0 / both + 1.0 / DECODE_PER_CORE)
        cores = req / per_core if per_core > 0 else float("inf")
        basis += (
            f"; MEASURED host rates (tools/bench_host_featurize.py, "
            f"{hb['size']}px step {hb['step']}): SIFT "
            f"{hb['sift_img_per_sec']} + LCS {hb['lcs_img_per_sec']} "
            f"img/s/core -> {per_core:.1f} img/s/core incl. decode "
            f"=> ~{cores:,.0f} cores fleet-wide "
            f"(~{cores / 8:,.0f}/host on 8 hosts)"
        )
    else:
        basis += "; host descriptor rates unmeasured (run bench_host_featurize)"
    rows.append(
        {
            "stage": "host decode+SIFT+LCS",
            "minutes": round(remaining / 60, 2),
            "basis": basis,
        }
    )
    # Variant: --sift-backend xla moves dense SIFT onto the chips (LCS is
    # already a device program), leaving the hosts ONLY JPEG decode. The
    # on-chip SIFT adds ~1.3e8 conv FLOPs/image (two grouped 1-D convs
    # over an 8-channel orientation map) ≈ 5e12 FLOPs/chip total — a few
    # chip-seconds, bounded like the PCA/GMM row.
    rows.append(
        {
            "stage": "host decode ONLY (--sift-backend xla variant)",
            "minutes": round(remaining / 60, 2),
            "basis": f"with on-chip SIFT (ops/sift_xla.py): hosts need only "
            f"{req / DECODE_PER_CORE:,.0f} cores fleet-wide at the measured "
            f"{DECODE_PER_CORE:.0f} img/s/core decode rate; on-chip "
            "SIFT+LCS bounded at ~0.2 min across 64 chips",
        }
    )

    out = {
        "metric": "imagenet_northstar_projection_minutes",
        "note": "PROJECTION from measured single-chip rates; not a measurement",
        "target_minutes": args.budget_min,
        "baseline_minutes": 100.0,
        "chip_stages_minutes": chip_minutes,
        "stages": rows,
    }
    # Measured END-TO-END anchor (VERDICT r3 missing #6): the pipeline_rate
    # checkride step runs the whole featurize→FV→solve program on one chip
    # at full per-image geometry. Its img/s cross-checks the sum-of-stage
    # model above — if the anchor disagrees with the stage sum, trust the
    # anchor.
    pr = _tpu(steps, "pipeline_rate")
    if pr and pr.get("featurize_img_per_sec"):
        img_s = float(pr["featurize_img_per_sec"])
        anchor_min = N_IMAGES / (img_s * CHIPS * SCALING_EFFICIENCY) / 60.0
        out["end_to_end_anchor"] = {
            "measured_img_per_sec_per_chip": img_s,
            "config": pr.get("config"),
            "stages_s": pr.get("stages_s"),
            "projected_chip_featurize_minutes_v5e64": round(anchor_min, 2),
            "basis": f"measured(tpu) end-to-end chip featurize "
            f"(on-chip SIFT+LCS+PCA+FV) x {CHIPS} chips x "
            f"{SCALING_EFFICIENCY} eff (assumed)",
        }
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
