"""Multi-device data-parallel fit bench: the mesh-native scaling evidence.

The ISSUE-13 tentpole claim, measured. A canonical two-branch jittable
featurize → block-least-squares pipeline (the ImageNet SIFT|LCS shape at
bench scale, all-device math so the mesh actually carries the work) is
fitted in TWO subprocesses — one forced to a single XLA host device, one
to ``--devices`` fake devices (``XLA_FLAGS=
--xla_force_host_platform_device_count=N``, the test_multihost precedent)
— and each subprocess A/Bs the SHARDED walk (``config.shard_data_batches
= True``: explicit SpecLayout ``in_shardings``/``out_shardings`` on the
fused chain, mask-padded non-divisible batches) against the SINGLE-DEVICE
walk (``= False``: host batches, placement-inherited lowering).

Gates:

- **bit-identity (hard, always, both device counts)**: the sharded walk's
  held-out predictions must be byte-equal to the single-device walk's —
  explicit specs, mask-padding, and the psum'd intercept/gram path must
  be numerically invisible. (Across DIFFERENT device counts the psum
  fold order legitimately differs, so cross-count parity is reported as
  a max-rel-error, not gated bitwise.)
- **no silent fallback (hard, always)**: the N-device sharded fit must
  record ZERO ``sharding.fallback_small_batch`` counts and at least one
  sharded/padded chain lowering — registry-counter-verified, the
  "no silent single-device cliff" contract.
- **rows/s scaling (hardware-conditional)**: sharded-fit featurize+solve
  rows/s at N devices over rows/s at 1 device. Hard (>= 0.7 * N/2) only
  on real multi-chip hardware (backend != cpu); on a CPU host the N fake
  devices time-slice the same cores, so the gate is soft (>= 0.4 — the
  mesh must not make things pathologically slower), the PR-5/PR-9
  hardware-conditional precedent.

The result row APPENDS to ``--out`` (BENCH_fit.json) as a fingerprinted
JSONL ``fit_multichip`` row — ``make bench-watch`` fits noise bands over
prior rows (rows/s & scaling down = regress, ``bit_identical``
true→false = regress).

Usage: python tools/bench_multichip.py [--devices 8] [--reps 3]
           [--quick] [--out BENCH_fit.json]
Prints one JSON line; exit 1 on a failed hard gate.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: The per-device-count worker: everything that must run under a forced
#: device count lives here; results come back as one JSON line. The
#: pipeline is all jittable device math (random-feature matmul + tanh
#: chains, two branches, gather, block least squares) so the mesh — not a
#: host featurizer — carries the work.
_WORKER = textwrap.dedent(
    """
    import json, statistics, sys, time

    import jax
    if {force_cpu!r}:
        # The axon sitecustomize force-registers the TPU platform ignoring
        # JAX_PLATFORMS; overriding the config is the reliable switch (the
        # tests/conftest.py precedent).
        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp

    from keystone_tpu.config import config
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
    from keystone_tpu.utils.metrics import sharding_counters
    from keystone_tpu.workflow.executor import PipelineEnv
    from keystone_tpu.workflow.pipeline import Pipeline, Transformer

    rows, dim, hidden, classes, reps = {rows}, {dim}, {hidden}, {classes}, {reps}

    class RandomFeatures(Transformer):
        def __init__(self, seed, width):
            self.seed, self.width = int(seed), int(width)
            rng = np.random.default_rng(self.seed)
            self._W = jnp.asarray(
                rng.normal(size=(dim, width)).astype(np.float32)
            )
        def signature(self):
            return self.stable_signature(self.seed, self.width)
        def apply_batch(self, X):
            Y = jnp.tanh(X @ self._W)
            return Y / (1.0 + jnp.abs(Y))

    # ONE set of transformer/estimator instances for every rep and both
    # walks: per-instance jit caches (_jit_cache / _shard_jit_cache) stay
    # warm across the per-rep PipelineEnv resets, so the timed walls
    # measure execution, not re-tracing. Only the fitted mapper produced
    # by each fit retraces its apply — identically in both walks.
    branch_a = RandomFeatures(1, hidden)
    branch_b = RandomFeatures(2, hidden)
    estimator = BlockLeastSquaresEstimator(
        block_size=2 * hidden, num_iters=1, lam=1e-3
    )

    def build(X, y):
        feat = Pipeline.gather(
            [branch_a.to_pipeline(), branch_b.to_pipeline()]
        )
        return feat.and_then(estimator, X, y)

    rng = np.random.default_rng(0)
    X = rng.normal(size=(rows, dim)).astype(np.float32)
    W_true = rng.normal(size=(dim, classes)).astype(np.float32)
    y = (X @ W_true + 0.01 * rng.normal(size=(rows, classes))).astype(
        np.float32
    )
    # Deliberately NON-divisible held-out rows: every bench run exercises
    # the mask-pad path (the old silent cliff) under the bit-identity gate.
    X_test = rng.normal(size=(210, dim)).astype(np.float32)

    def timed_fit(shard):
        PipelineEnv.reset()
        config.shard_data_batches = shard
        t0 = time.perf_counter()
        fitted = build(X, y).fit()
        preds = np.asarray(fitted.apply(X_test).get())
        wall = time.perf_counter() - t0
        return wall, preds

    # Warmup both walks (jit caches are process-wide): compile cost must
    # not masquerade as a scaling difference.
    timed_fit(False); timed_fit(True)

    unshard_walls, shard_walls = [], []
    preds_unshard = preds_shard = None
    sharding_counters.reset()
    for _ in range(reps):
        w, preds_unshard = timed_fit(False)
        unshard_walls.append(w)
    counters_unshard = dict(sharding_counters.snapshot())
    sharding_counters.reset()
    for _ in range(reps):
        w, preds_shard = timed_fit(True)
        shard_walls.append(w)
    counters_shard = dict(sharding_counters.snapshot())

    import hashlib
    out = {{
        "devices": len(jax.devices()),
        "backend": jax.default_backend(),
        "unshard_wall_s": statistics.median(unshard_walls),
        "shard_wall_s": statistics.median(shard_walls),
        "rows_per_s_sharded": rows / statistics.median(shard_walls),
        "bit_identical": bool(np.array_equal(preds_unshard, preds_shard)),
        "preds_digest": hashlib.sha256(preds_shard.tobytes()).hexdigest(),
        "preds_norm": float(np.linalg.norm(preds_shard)),
        "preds_sample": [float(v) for v in preds_shard.ravel()[:8]],
        "counters_sharded": counters_shard,
        "counters_unsharded": counters_unshard,
    }}
    print("MULTICHIP_ROW " + json.dumps(out), flush=True)
    """
)


def _run_worker(n_devices: int, args) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_devices}"
    )
    src = _WORKER.format(
        force_cpu=True, rows=args.rows, dim=args.dim, hidden=args.hidden,
        classes=args.classes, reps=args.reps,
    )
    proc = subprocess.run(
        [sys.executable, "-c", src], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=600,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"{n_devices}-device worker failed rc={proc.returncode}\n"
            f"stdout:{proc.stdout[-1000:]}\nstderr:{proc.stderr[-2000:]}"
        )
    for line in proc.stdout.splitlines():
        if line.startswith("MULTICHIP_ROW "):
            return json.loads(line[len("MULTICHIP_ROW "):])
    raise RuntimeError(
        f"{n_devices}-device worker printed no row\n"
        f"stdout:{proc.stdout[-1000:]}"
    )


def run_bench(args) -> dict:
    one = _run_worker(1, args)
    multi = _run_worker(args.devices, args)

    scaling = (
        multi["rows_per_s_sharded"] / one["rows_per_s_sharded"]
        if one["rows_per_s_sharded"] > 0 else float("inf")
    )
    bit_identical = bool(one["bit_identical"] and multi["bit_identical"])
    fallbacks = int(
        multi["counters_sharded"].get("fallback_small_batch", 0)
    )
    sharded_lowerings = int(
        multi["counters_sharded"].get("sharded_chain_calls", 0)
    )
    no_silent_fallback = fallbacks == 0 and sharded_lowerings > 0
    # Cross-device-count parity: the psum fold order differs by width, so
    # this is a tolerance check, not a bit gate.
    cross_rel = abs(multi["preds_norm"] - one["preds_norm"]) / max(
        one["preds_norm"], 1e-12
    )

    # Hardware-conditional scaling gate (the PR-5/PR-9 precedent): fake
    # CPU devices time-slice the same host cores, so near-linear scaling
    # is only demandable on real multi-chip hardware.
    gate_is_hard = multi["backend"] != "cpu"
    bound = 0.7 * args.devices / 2 if gate_is_hard else 0.4
    scaling_gate = scaling >= bound

    from keystone_tpu.utils.metrics import environment_fingerprint

    row = {
        "metric": "fit_multichip",
        "value": round(scaling, 3),
        "unit": (
            "x rows_per_s scaling "
            f"({args.devices}-device sharded fit / 1-device sharded fit)"
        ),
        "backend": multi["backend"],
        "host_cores": os.cpu_count() or 1,
        "n_devices": args.devices,
        "env": environment_fingerprint(devices=False),
        "detail": {
            "rows": args.rows,
            "dim": args.dim,
            "hidden": args.hidden,
            "classes": args.classes,
            "reps": args.reps,
            "rows_per_s_1dev": round(one["rows_per_s_sharded"], 2),
            "rows_per_s_ndev": round(multi["rows_per_s_sharded"], 2),
            "wall_s_1dev": round(one["shard_wall_s"], 4),
            "wall_s_ndev": round(multi["shard_wall_s"], 4),
            "bit_identical": bit_identical,
            "shard_fallbacks": fallbacks,
            "sharded_chain_calls": sharded_lowerings,
            "batches_padded": int(
                multi["counters_sharded"].get("batches_padded", 0)
            ),
            "pad_rows_added": int(
                multi["counters_sharded"].get("pad_rows_added", 0)
            ),
            "no_silent_fallback": no_silent_fallback,
            "cross_devcount_rel_err": round(cross_rel, 9),
            "scaling_gate": scaling_gate,
            "scaling_gate_is_hard": gate_is_hard,
        },
    }
    row["ok"] = bool(
        bit_identical
        and no_silent_fallback
        and (scaling_gate or getattr(args, "quick", False))
    )
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="multi-device data-parallel fused-chain fit bench"
    )
    ap.add_argument("--devices", type=int, default=8,
                    help="forced fake-device mesh width for the wide run")
    ap.add_argument("--reps", type=int, default=3,
                    help="fits per walk per worker; medians reported")
    ap.add_argument("--rows", type=int, default=4096)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--classes", type=int, default=8)
    ap.add_argument("--quick", action="store_true",
                    help="tiny problem, 1 rep — harness validation only, "
                         "no row is written and the scaling gate is soft")
    ap.add_argument("--out", default=None,
                    help="append the fingerprinted JSONL row here")
    args = ap.parse_args(argv)

    if args.quick:
        args.rows, args.dim, args.hidden = 522, 32, 48
        args.classes, args.reps = 4, 1

    row = run_bench(args)
    print(json.dumps(row), flush=True)

    if args.out and not args.quick:
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")

    d = row["detail"]
    if not d["bit_identical"]:
        print("GATE FAILED: sharded fit predictions differ from the "
              "single-device walk", file=sys.stderr)
        return 1
    if not d["no_silent_fallback"]:
        print(
            "GATE FAILED: sharded fit fell back single-device "
            f"(fallbacks={d['shard_fallbacks']}, "
            f"sharded_chain_calls={d['sharded_chain_calls']})",
            file=sys.stderr,
        )
        return 1
    if not d["scaling_gate"] and not args.quick:
        kind = "hard" if d["scaling_gate_is_hard"] else "soft"
        print(
            f"GATE FAILED: rows/s scaling {row['value']}x below the "
            f"{kind} bound at {row['n_devices']} devices",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
