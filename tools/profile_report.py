"""Render / demo the per-node resource-attribution profile.

The training-side half of observability (the serving half is PR-7's
trace_report + metrics server): ``utils.metrics.ResourceProfile``
attributes wall time, device wait, cost-model FLOPs/bytes (from the
memoized compiled ``cost_analysis``/``memory_analysis``), output nbytes,
and HBM high-water deltas to every pipeline node an executor walk runs.
This CLI renders a profile export as the trace_report-style attribution
table — the SAME renderer ``tools/trace_report.py --fit`` uses over a
Chrome trace, so a live profile and a trace of the same fit read
identically.

Modes:

    python tools/profile_report.py PROFILE.json [--top N]
        Render a ``ResourceProfile.export()`` JSON file. Exit 1 on a
        schema-valid-but-empty profile (a dead profiler must fail
        loudly, not print a clean empty table — the trace_report rule).

    python tools/profile_report.py --decisions
        The explain-plan surface of the profile-guided optimizer
        (ISSUE-12): run the canonical re-used-subchain pipeline through
        the full profile-once-optimize-forever loop in-process — a
        ``fit(profile=True)`` persists the measured per-node profile to
        a private store, then a fresh optimization consumes it — and
        render every recorded ``OptimizerDecision`` (rule, node, chosen
        action, cost provenance measured/sampled/model, measured-vs-
        modeled cost numbers, reason). Exit 1 when the decision log
        stays empty or no decision carries measured provenance (a dead
        loop must fail loudly, the trace_report rule).

    python tools/profile_report.py --demo [--out PROFILE.json]
        The ``make profile-demo`` smoke, also run in-process by tier-1
        (tests/test_profile.py): a small fit + apply of a canonical
        fused pipeline under the profiler, gated on

        - every executed node producing an attribution row with nonzero
          wall time;
        - the solve node's cost-model FLOPs within 2x of the
          ``achieved_tflops`` oracle for the same computation;
        - KEYSTONE_PROFILE=0 outputs bit-identical to profiled ones
          (the profiler measures, never perturbs);
        - a kill-mid-solve chaos run auto-dumping a flight-recorder
          journey that names the last completed chunk;
        - the registry's Prometheus exposition (now carrying the
          keystone_profile_node_* families) still validating.

Exit status: 0 = rendered / all demo gates green, 1 = failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def render(doc: dict, top: int = 0) -> str:
    """The attribution table of an exported profile document."""
    from keystone_tpu.utils.metrics import render_attribution_table

    rows = doc.get("rows", [])
    if top > 0:
        rows = rows[:top]
    return render_attribution_table(rows)


def render_decision_table(decisions) -> str:
    """The optimizer's explain-plan: one row per recorded
    ``OptimizerDecision`` (workflow/rules.py), column-aligned like the
    attribution table. ``cost`` renders as compact key=value pairs —
    the measured-vs-modeled numbers behind the choice."""
    headers = ("rule", "node", "action", "provenance", "reason / cost")
    rows = []
    for d in decisions:
        cost = " ".join(f"{k}={v}" for k, v in sorted(d.cost.items()))
        why = d.reason + (f"  [{cost}]" if cost else "")
        rows.append((d.rule, d.node, d.action, d.provenance, why))
    if not rows:
        return "(no optimizer decisions recorded)"
    widths = [
        max(len(headers[i]), *(len(r[i]) for r in rows))
        for i in range(len(headers) - 1)
    ]
    lines = [
        "  ".join(h.ljust(w) for h, w in zip(headers, widths))
        + "  " + headers[-1],
        "  ".join("-" * w for w in widths) + "  " + "-" * len(headers[-1]),
    ]
    for r in rows:
        lines.append(
            "  ".join(c.ljust(w) for c, w in zip(r, widths)) + "  " + r[-1]
        )
    return "\n".join(lines)


def run_decisions_demo() -> dict:
    """The ``--decisions`` flow: close the cost-model loop on the
    canonical re-used-subchain pipeline in-process and render what the
    optimizer decided from the measurements. Returns the verdict dict.
    """
    import tempfile

    import numpy as np

    from keystone_tpu.config import config
    from keystone_tpu.workflow import rules
    from keystone_tpu.workflow.executor import PipelineEnv
    from keystone_tpu.workflow.pipeline import Pipeline, Transformer

    class HostWork(Transformer):
        """Deterministic host-bound featurizer (fixed iteration count)."""

        jittable = False

        def __init__(self, seed: int, iters: int):
            self.seed, self.iters = int(seed), int(iters)

        def signature(self):
            return self.stable_signature(self.seed, self.iters)

        def apply_batch(self, X):
            Y = np.asarray(X, dtype=np.float32)
            rng = np.random.default_rng(self.seed)
            filt = (1.0 + rng.uniform(size=Y.shape[1] // 2 + 1)).astype(
                np.complex64
            )
            for _ in range(self.iters):
                spec = np.fft.rfft(Y, axis=1) * filt
                Y = np.tanh(Y + np.fft.irfft(
                    spec, n=Y.shape[1], axis=1
                ).astype(np.float32))
            return Y

    class ScaleBy(Transformer):
        jittable = True

        def __init__(self, c: float):
            self.c = float(c)

        def signature(self):
            return self.stable_signature(self.c)

        def apply_batch(self, X):
            return X * self.c

    from keystone_tpu.nodes.learning.linear_mapper import LinearMapEstimator

    rng = np.random.default_rng(0)
    X = rng.normal(size=(256, 64)).astype(np.float32)
    Y = (X @ rng.normal(size=(64, 4))).astype(np.float32)

    def build():
        prefix = HostWork(seed=1, iters=12).to_pipeline()
        branches = [prefix.and_then(ScaleBy(2.0)),
                    prefix.and_then(ScaleBy(0.5))]
        return Pipeline.gather(branches).and_then(
            LinearMapEstimator(lam=1e-3), X, Y
        )

    store = tempfile.mkdtemp(prefix="keystone_decisions_demo_")
    # Env-level isolation: the env var wins over config.profile_store,
    # so only it guarantees the demo never touches a user-exported store.
    prev_env = os.environ.get("KEYSTONE_PROFILE_STORE")
    prev_cache = config.auto_cache
    try:
        os.environ["KEYSTONE_PROFILE_STORE"] = store
        # Profile once: the measured rows the next optimization consumes.
        PipelineEnv.reset()
        fitted = build().fit(profile=True)
        saved = getattr(fitted, "fit_profile", None)
        # Optimize forever (well, once more): fresh session, measured hit.
        PipelineEnv.reset()
        config.auto_cache = True
        rules.clear_decisions()
        refit = build().fit()
        # The optimizer plans at FIT time; applies run plain and hit the
        # session cache through the executor's discovery cut (re-running
        # whole-pipeline optimization per apply would re-pay sampling).
        config.auto_cache = False
        refit.apply(X[:64]).get()
        decisions = rules.optimizer_decisions()
    finally:
        if prev_env is None:
            os.environ.pop("KEYSTONE_PROFILE_STORE", None)
        else:
            os.environ["KEYSTONE_PROFILE_STORE"] = prev_env
        config.auto_cache = prev_cache
        PipelineEnv.reset()
        import shutil

        shutil.rmtree(store, ignore_errors=True)

    result = {
        "metric": "optimizer_decisions",
        "decisions": len(decisions),
        "store_entry_saved": bool(saved is not None and saved.saved_to),
        "pass": {
            "decision_log_nonempty": bool(decisions),
            "measured_provenance_present": any(
                d.provenance == "measured" for d in decisions
            ),
            "cache_decision_present": any(
                d.action.startswith("cache-") for d in decisions
            ),
        },
    }
    result["ok"] = all(result["pass"].values())
    result["table"] = render_decision_table(decisions)
    return result


def run_demo(out_path: str | None = None) -> dict:
    """The profile-demo flow; returns the verdict dict (``ok`` + every
    gate). Uses fresh PipelineEnvs so both runs really execute."""
    import glob
    import tempfile

    import numpy as np

    from keystone_tpu.config import config
    from keystone_tpu.linalg import solve_least_squares_chunked
    from keystone_tpu.nodes.learning.linear_mapper import LinearMapEstimator
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.scalers import StandardScaler
    from keystone_tpu.utils import flight_recorder
    from keystone_tpu.utils.metrics import (
        achieved_tflops,
        metrics_registry,
        profile_scope,
        resource_profile,
        validate_prometheus_text,
    )
    from keystone_tpu.workflow.executor import PipelineEnv
    from keystone_tpu.workflow.pipeline import FusedTransformer

    rng = np.random.default_rng(0)
    n, d, k = 256, 32, 4
    X = rng.normal(size=(n, d)).astype(np.float32)
    Y = (X @ rng.normal(size=(d, k))).astype(np.float32)

    def build():
        feats = StandardScaler().with_data(X).and_then(L2Normalizer())
        return feats.and_then(LinearMapEstimator(lam=1e-3), X, Y)

    # Reference run, profiler OFF (KEYSTONE_PROFILE=0 semantics).
    PipelineEnv.reset()
    baseline = build().fit().apply(X).get()
    baseline_bytes = np.asarray(baseline).tobytes()

    # Profiled run: fresh env so every node really executes.
    PipelineEnv.reset()
    resource_profile.reset()
    with profile_scope():
        fitted = build().fit()
        profiled = fitted.apply(X).get()
    profiled_bytes = np.asarray(profiled).tobytes()

    rows = resource_profile.rows()
    by_node = {r["node"]: r for r in rows}
    executed = [r for r in rows if r["executed"] > 0]

    # The solve node: the (possibly fused) transformer program containing
    # the fitted LinearMapper, executed by the apply.
    solve_rows = [r for r in rows if "LinearMapper" in r["node"]
                  and r["executed"] > 0 and r["flops"]]
    flops_ratio = None
    if solve_rows:
        solve_row = solve_rows[0]
        chain = fitted.transformers()
        fused = chain[0] if len(chain) == 1 else FusedTransformer(chain)
        oracle = achieved_tflops(fused.apply_batch, X)
        per_call = solve_row["flops"] / max(1, solve_row["executed"])
        if oracle["flops"] > 0:
            flops_ratio = per_call / oracle["flops"]

    # Kill-mid-solve chaos: a producer that dies at chunk 3 must leave a
    # solver flight-recorder dump naming the last completed chunk.
    tmp = tempfile.mkdtemp(prefix="keystone_profile_demo_")
    prior_dir = config.flight_dir
    died_at = 3

    def dying_stream():
        for i in range(8):
            if i == died_at:
                raise RuntimeError("injected mid-solve death")
            yield (X[i * 32:(i + 1) * 32], Y[i * 32:(i + 1) * 32])

    death_seen = False
    last_chunk = None
    dump_outcome = None
    # try/finally: the demo runs in-process under tier-1 — a leaked
    # flight_dir override would contaminate every later test's dumps.
    try:
        config.flight_dir = tmp
        flight_recorder.reset_solver_recorder()
        try:
            solve_least_squares_chunked(dying_stream(), lam=1e-3,
                                        prefetch_depth=0)
        except RuntimeError:
            death_seen = True
        dumps = sorted(
            glob.glob(os.path.join(tmp, "keystone_flight_solver_*"))
        )
        if dumps:
            dump_doc = json.load(open(dumps[-1]))
            for rec in dump_doc.get("records", []):
                if rec.get("kind") == "lsq_chunked":
                    last_chunk = rec.get("units_done")
                    dump_outcome = rec.get("outcome")
    finally:
        config.flight_dir = prior_dir
        flight_recorder.reset_solver_recorder()

    prom_errors = validate_prometheus_text(metrics_registry.prometheus())

    result = {
        "metric": "profile_demo",
        "nodes": len(rows),
        "executed_nodes": len(executed),
        "node_labels": sorted(by_node),
        "solve_node": solve_rows[0]["node"] if solve_rows else None,
        "flops_ratio_vs_oracle": (
            round(flops_ratio, 4) if flops_ratio is not None else None
        ),
        "chaos_dump": dumps[-1] if dumps else None,
        "chaos_last_chunk": last_chunk,
        "pass": {
            "every_executed_node_has_nonzero_wall": bool(executed) and all(
                r["wall_ms"] > 0 for r in executed
            ),
            "fit_and_apply_nodes_covered": any(
                r["node"].endswith(".fit") for r in rows
            ) and bool(solve_rows) and "Dataset" in by_node,
            "solve_flops_within_2x_oracle": (
                flops_ratio is not None and 0.5 <= flops_ratio <= 2.0
            ),
            "profile_off_bit_identical": profiled_bytes == baseline_bytes,
            "chaos_dump_names_last_chunk": (
                death_seen and last_chunk == died_at
                and dump_outcome == "error:RuntimeError"
            ),
            "prometheus_valid": not prom_errors,
        },
    }
    result["ok"] = all(result["pass"].values())
    if out_path:
        resource_profile.export(out_path)
        result["profile_out"] = out_path
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("profile", nargs="?", default=None,
                    help="ResourceProfile.export() JSON to render")
    ap.add_argument("--top", type=int, default=0,
                    help="only the N heaviest-wall rows")
    ap.add_argument("--demo", action="store_true",
                    help="run the gated profile-demo instead of rendering")
    ap.add_argument("--decisions", action="store_true",
                    help="close the cost-model loop on the canonical "
                         "re-used-subchain pipeline and print the "
                         "optimizer's decision table (explain-plan)")
    ap.add_argument("--out", default=None,
                    help="demo: also export the profile JSON here")
    args = ap.parse_args(argv)

    if args.decisions:
        result = run_decisions_demo()
        table = result.pop("table")
        print(json.dumps(result))
        print("\n" + table, file=sys.stderr)
        if not result["ok"]:
            failed = [k for k, v in result["pass"].items() if not v]
            print(f"decisions: FAIL ({', '.join(failed)})", file=sys.stderr)
        return 0 if result["ok"] else 1

    if args.demo:
        result = run_demo(args.out)
        print(json.dumps(result))
        if result["ok"]:
            from keystone_tpu.utils.metrics import resource_profile

            print("\n" + resource_profile.table(), file=sys.stderr)
            print("\nprofile-demo: PASS", file=sys.stderr)
        else:
            failed = [k for k, v in result["pass"].items() if not v]
            print(f"profile-demo: FAIL ({', '.join(failed)})",
                  file=sys.stderr)
        return 0 if result["ok"] else 1

    if not args.profile:
        print("profile_report: a PROFILE.json path or --demo is required",
              file=sys.stderr)
        return 1
    with open(args.profile) as f:
        doc = json.load(f)
    rows = doc.get("rows")
    if not isinstance(rows, list) or not rows:
        # A dead profiler must fail loudly, not render a clean empty
        # table (the trace_report zero-span rule).
        print(
            f"EMPTY: {args.profile} contains no attribution rows — was "
            "KEYSTONE_PROFILE=1 (or fit(profile=True)) set for the run?",
            file=sys.stderr,
        )
        return 1
    print(json.dumps({"profile": args.profile, "rows": len(rows)}))
    print(render(doc, top=args.top), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
