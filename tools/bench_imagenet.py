"""ImageNet SIFT+LCS+FV+BlockLS multi-device bench: the flagship chain
as scaling + donation evidence.

The ISSUE-16 tentpole claim, measured on the REAL pipeline (not the
synthetic matmul stand-in of ``bench_multichip.py``): synthetic-scale
ImageNet images through the actual two-branch featurizer — native dense
SIFT / LCS fronts, PCA, the PALLAS Fisher-vector kernel, signed-sqrt +
L2 — into the class-balanced block weighted least squares solver. Each
worker subprocess runs under a forced fake-device count
(``XLA_FLAGS=--xla_force_host_platform_device_count=N``, the
``bench_multichip.py`` precedent) and A/Bs the sharded walk
(``config.shard_data_batches=True``: host descriptor batches staged onto
the mesh by the fused chain and donated where an output can alias them)
against the single-device walk; a third worker re-runs the wide mesh
with ``config.donate_buffers=False`` — the non-donated baseline the
KEYSTONE_DONATE_BUFFERS knob exists for.

Gates:

- **bit-identity (hard, always)**: sharded scores byte-equal to the
  single-device walk's at BOTH device counts, and the donated run
  byte-equal to the non-donated baseline — explicit specs, mask-padded
  scoring batches, staging donation, and the Pallas kernel must all be
  numerically invisible.
- **no silent fallback + Pallas active (hard, always)**: zero
  ``fallback_*`` counts, at least one sharded chain lowering, at least
  one ``pallas_sharded_calls`` (the FV kernel really ran on the sharded
  path), and at least one donation decision
  (``buffers_donated + donation_refused`` — the plumbing is live, with
  refusals counted, never silent).
- **rows/s scaling (hardware-conditional)**: hard (>= 0.7 * N/2) only on
  real hardware; soft (>= 0.25) on CPU fake devices, where the host
  SIFT/LCS fronts and time-sliced cores dominate (the PR-5/PR-9
  precedent).
- **peak HBM (hardware-conditional)**: donated run's
  ``peak_bytes_in_use`` strictly below the non-donated baseline's — only
  gateable where the runtime reports a peak (real hardware; CPU answers
  None, and the memory-attribution proof lives in
  tests/test_donated_fits.py via ``memory_analysis`` alias bytes).

The result row APPENDS to ``--out`` (BENCH_fit.json) as a fingerprinted
JSONL ``fit_imagenet_multichip`` row; ``make bench-watch`` learns the
family automatically (generic leaf flattening).

Usage: python tools/bench_imagenet.py [--devices 8] [--quick]
           [--out BENCH_fit.json]
Prints one JSON line; exit 1 on a failed hard gate.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Per-(device count, donate mode) worker. The whole flagship chain runs
#: in here; one JSON line comes back. Donation mode is decided before
#: anything lowers, so each subprocess's jit caches are pure per mode.
_WORKER = textwrap.dedent(
    """
    import hashlib, json, statistics, sys, time

    import jax
    if {force_cpu!r}:
        # The axon sitecustomize force-registers the TPU platform ignoring
        # JAX_PLATFORMS; overriding the config is the reliable switch (the
        # tests/conftest.py precedent).
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from keystone_tpu.config import config
    from keystone_tpu.loaders.imagenet import ImageNetLoader
    from keystone_tpu.nodes.learning import BlockWeightedLeastSquaresEstimator
    from keystone_tpu.nodes.util import ClassLabelIndicators
    from keystone_tpu.pipelines.images.imagenet_sift_lcs_fv import (
        ImageNetSiftLcsFVConfig,
        build_featurizer,
        resolve_scale,
    )
    from keystone_tpu.utils.metrics import peak_hbm_bytes, sharding_counters
    from keystone_tpu.workflow.executor import PipelineEnv

    n, classes, reps = {n}, {classes}, {reps}
    config.donate_buffers = {donate!r}

    conf = resolve_scale(ImageNetSiftLcsFVConfig(
        synthetic_n=n, synthetic_classes=classes,
        pca_dims={pca_dims}, gmm_k={gmm_k}, gmm_iters=2,
        descriptor_sample=20000, fv_backend="pallas", num_iters=1,
    ))
    train, test = ImageNetLoader.synthetic(n=n, num_classes=classes)
    # Non-divisible held-out rows: every scoring pass exercises the
    # mask-pad path under the bit-identity gate.
    X_test = test.data[: max(66, len(test.data) - 3)]
    targets = np.asarray(ClassLabelIndicators(classes)(train.labels))

    def timed_fit(shard):
        PipelineEnv.reset()
        config.shard_data_batches = shard
        t0 = time.perf_counter()
        featurizer = build_featurizer(conf, train.data)
        solver = BlockWeightedLeastSquaresEstimator(
            block_size=conf.block_size, num_iters=conf.num_iters,
            lam=conf.lam, mixture_weight=conf.mixture_weight,
        )
        scored = featurizer.and_then(solver, train.data, targets)
        preds = np.asarray(scored(X_test).get())
        return time.perf_counter() - t0, preds

    # Warmup both walks so compile cost can't masquerade as scaling.
    timed_fit(False); timed_fit(True)

    unshard_walls, shard_walls = [], []
    preds_unshard = preds_shard = None
    for _ in range(reps):
        w, preds_unshard = timed_fit(False)
        unshard_walls.append(w)
    sharding_counters.reset()
    for _ in range(reps):
        w, preds_shard = timed_fit(True)
        shard_walls.append(w)
    counters = dict(sharding_counters.snapshot())

    out = {{
        "devices": len(jax.devices()),
        "backend": jax.default_backend(),
        "donate": bool(config.donate_buffers),
        "unshard_wall_s": statistics.median(unshard_walls),
        "shard_wall_s": statistics.median(shard_walls),
        "rows_per_s_sharded": n / statistics.median(shard_walls),
        "bit_identical": bool(np.array_equal(preds_unshard, preds_shard)),
        "preds_digest": hashlib.sha256(preds_shard.tobytes()).hexdigest(),
        "preds_norm": float(np.linalg.norm(preds_shard)),
        "counters": counters,
        "peak_hbm_bytes": peak_hbm_bytes(),
    }}
    print("IMAGENET_ROW " + json.dumps(out), flush=True)
    """
)


def _run_worker(n_devices: int, donate: bool, args) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_devices}"
    )
    src = _WORKER.format(
        force_cpu=True, donate=donate, n=args.images,
        classes=args.classes, pca_dims=args.pca_dims, gmm_k=args.gmm_k,
        reps=args.reps,
    )
    proc = subprocess.run(
        [sys.executable, "-c", src], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=900,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"{n_devices}-device donate={donate} worker failed "
            f"rc={proc.returncode}\n"
            f"stdout:{proc.stdout[-1000:]}\nstderr:{proc.stderr[-2000:]}"
        )
    for line in proc.stdout.splitlines():
        if line.startswith("IMAGENET_ROW "):
            return json.loads(line[len("IMAGENET_ROW "):])
    raise RuntimeError(
        f"{n_devices}-device donate={donate} worker printed no row\n"
        f"stdout:{proc.stdout[-1000:]}"
    )


def run_bench(args) -> dict:
    one = _run_worker(1, True, args)
    multi = _run_worker(args.devices, True, args)
    baseline = _run_worker(args.devices, False, args)

    scaling = (
        multi["rows_per_s_sharded"] / one["rows_per_s_sharded"]
        if one["rows_per_s_sharded"] > 0 else float("inf")
    )
    bit_identical = bool(one["bit_identical"] and multi["bit_identical"])
    donation_invisible = bool(
        multi["preds_digest"] == baseline["preds_digest"]
    )
    c = multi["counters"]
    fallbacks = int(c.get("fallback_small_batch", 0)) + int(
        c.get("fallback_row_coupled", 0)
    )
    sharded_lowerings = int(c.get("sharded_chain_calls", 0))
    pallas_calls = int(c.get("pallas_sharded_calls", 0))
    donation_decisions = int(c.get("buffers_donated", 0)) + int(
        c.get("donation_refused", 0)
    )
    no_silent_fallback = fallbacks == 0 and sharded_lowerings > 0

    gate_is_hard = multi["backend"] != "cpu"
    bound = 0.7 * args.devices / 2 if gate_is_hard else 0.25
    scaling_gate = scaling >= bound

    # Peak-HBM gate: only where the runtime reports a peak (real
    # hardware). CPU answers None; the donated-below-undonated memory
    # proof there is the memory_analysis alias-bytes test in
    # tests/test_donated_fits.py.
    peak_d, peak_u = multi["peak_hbm_bytes"], baseline["peak_hbm_bytes"]
    peak_gate = True
    if gate_is_hard and peak_d is not None and peak_u is not None:
        peak_gate = peak_d < peak_u

    from keystone_tpu.utils.metrics import environment_fingerprint

    row = {
        "metric": "fit_imagenet_multichip",
        "value": round(scaling, 3),
        "unit": (
            "x rows_per_s scaling "
            f"({args.devices}-device sharded fit / 1-device sharded fit)"
        ),
        "backend": multi["backend"],
        "host_cores": os.cpu_count() or 1,
        "n_devices": args.devices,
        "env": environment_fingerprint(devices=False),
        "detail": {
            "images": args.images,
            "classes": args.classes,
            "pca_dims": args.pca_dims,
            "gmm_k": args.gmm_k,
            "reps": args.reps,
            "fv_backend": "pallas",
            "rows_per_s_1dev": round(one["rows_per_s_sharded"], 2),
            "rows_per_s_ndev": round(multi["rows_per_s_sharded"], 2),
            "wall_s_1dev": round(one["shard_wall_s"], 4),
            "wall_s_ndev": round(multi["shard_wall_s"], 4),
            "bit_identical": bit_identical,
            "donation_invisible": donation_invisible,
            "shard_fallbacks": fallbacks,
            "sharded_chain_calls": sharded_lowerings,
            "pallas_sharded_calls": pallas_calls,
            "buffers_donated": int(c.get("buffers_donated", 0)),
            "donation_refused": int(c.get("donation_refused", 0)),
            "batches_padded": int(c.get("batches_padded", 0)),
            "pad_rows_added": int(c.get("pad_rows_added", 0)),
            "no_silent_fallback": no_silent_fallback,
            "peak_hbm_donated": peak_d,
            "peak_hbm_undonated": peak_u,
            "peak_gate": peak_gate,
            "scaling_gate": scaling_gate,
            "scaling_gate_is_hard": gate_is_hard,
        },
    }
    row["ok"] = bool(
        bit_identical
        and donation_invisible
        and no_silent_fallback
        and pallas_calls > 0
        and donation_decisions > 0
        and peak_gate
        and (scaling_gate or getattr(args, "quick", False))
    )
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="multi-device ImageNet SIFT+LCS+FV+BlockLS fit bench"
    )
    ap.add_argument("--devices", type=int, default=8,
                    help="forced fake-device mesh width for the wide run")
    ap.add_argument("--reps", type=int, default=1,
                    help="fits per walk per worker; medians reported")
    ap.add_argument("--images", type=int, default=128,
                    help="synthetic train images (mesh-divisible)")
    ap.add_argument("--classes", type=int, default=8)
    ap.add_argument("--pca-dims", dest="pca_dims", type=int, default=8)
    ap.add_argument("--gmm-k", dest="gmm_k", type=int, default=4)
    ap.add_argument("--quick", action="store_true",
                    help="tiny problem — harness validation only, no row "
                         "is written and the scaling gate is soft")
    ap.add_argument("--out", default=None,
                    help="append the fingerprinted JSONL row here")
    args = ap.parse_args(argv)

    if args.quick:
        args.images, args.classes = 80, 4
        args.pca_dims, args.gmm_k, args.reps = 4, 2, 1

    row = run_bench(args)
    print(json.dumps(row), flush=True)

    if args.out and not args.quick:
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")

    d = row["detail"]
    if not d["bit_identical"]:
        print("GATE FAILED: sharded fit scores differ from the "
              "single-device walk", file=sys.stderr)
        return 1
    if not d["donation_invisible"]:
        print("GATE FAILED: donated fit scores differ from the "
              "non-donated baseline", file=sys.stderr)
        return 1
    if not d["no_silent_fallback"]:
        print(
            "GATE FAILED: sharded fit fell back single-device "
            f"(fallbacks={d['shard_fallbacks']}, "
            f"sharded_chain_calls={d['sharded_chain_calls']})",
            file=sys.stderr,
        )
        return 1
    if d["pallas_sharded_calls"] <= 0:
        print("GATE FAILED: the Pallas FV kernel never ran on the "
              "sharded path", file=sys.stderr)
        return 1
    if d["buffers_donated"] + d["donation_refused"] <= 0:
        print("GATE FAILED: no donation decision recorded — the donated "
              "lowering plumbing is not live", file=sys.stderr)
        return 1
    if not d["peak_gate"]:
        print(
            "GATE FAILED: donated peak HBM "
            f"{d['peak_hbm_donated']} not below non-donated "
            f"{d['peak_hbm_undonated']}",
            file=sys.stderr,
        )
        return 1
    if not d["scaling_gate"] and not args.quick:
        kind = "hard" if d["scaling_gate_is_hard"] else "soft"
        print(
            f"GATE FAILED: rows/s scaling {row['value']}x below the "
            f"{kind} bound at {row['n_devices']} devices",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
