#!/usr/bin/env python3
"""bench-watch: the bench regression sentinel (``make bench-watch``).

The repo's bench history — ``BENCH_r*.json`` round snapshots,
``MULTICHIP_r*.json`` dryrun verdicts, and the ``BENCH_serve.json`` /
``BENCH_fit.json`` JSONL rows — has so far been an archive: every PR
appends fingerprinted evidence, nothing reads it back. This tool turns the trajectory into a
GATE: it parses every history row, fits a per-metric noise band from the
recorded runs, and exits nonzero with a named-metric report when the
LATEST row of any series regresses outside its band.

Pure stdlib (json/glob/statistics): no jax import, so it runs anywhere —
CI, a laptop, the TPU host — in milliseconds.

How a series is judged
----------------------

- Every numeric leaf of every row becomes a series
  ``<file-family>:<metric>:<dotted.path>`` (booleans too — a gate flag
  that flips true→false is a regression by definition).
- Only fingerprint-COMPATIBLE history feeds a band: rows recorded under
  a different backend, device count, or host core count than the latest
  row are excluded (and reported as skipped), so a TPU round can never
  flag a CPU round as a regression — the refusal the
  ``environment_fingerprint`` provenance blocks exist for.
- The band over history values ``h``: ``[min(h), max(h)]`` widened by a
  relative margin ``max(BASE_MARGIN, CV_K * cv(h))`` — noisier series
  earn wider bands, quiet ones stay tight.
- Direction comes from the leaf name (and the row's ``unit`` field):
  latency-like leaves (``*_ms``, ``p99``, ``seconds``, ``wall``…)
  regress ABOVE the band; throughput-like leaves (``tflops``,
  ``rows_per_s``, ``speedup``…) regress BELOW it. Leaves matching
  neither list are tracked but never gated (reported as unjudged).
- A series with no comparable history passes vacuously: the sentinel
  gates the trajectory, it cannot invent a baseline.

Blessing an intentional change
------------------------------

A real perf trade (e.g. a latency increase bought for throughput) is
recorded, not reverted: ``--bless 'SERIES' --why 'reason'`` writes the
series' current latest value into ``tools/bench_watch_bless.json``; the
gate then waives that series while its latest value stays within
``BLESS_TOL`` of the blessed value. Once enough post-change history
accumulates, delete the entry — the band has re-fit around the new
regime.

Usage:
    python tools/bench_watch.py [--root DIR] [--json] [--verbose]
    python tools/bench_watch.py --bless SERIES --why "reason"

Exit status: 0 = no regression, 1 = regression(s) (named on stderr),
2 = usage / unreadable history.
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import re
import statistics
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BLESS_FILE = os.path.join("tools", "bench_watch_bless.json")

#: Base relative noise margin on every band; a 2x move always breaches.
BASE_MARGIN = 0.5
#: Widening per coefficient of variation of the history (noisy series
#: earn wider bands).
CV_K = 3.0
#: Hard cap so even a wildly noisy series still catches a 2x regression.
MAX_MARGIN = 0.9
#: A blessed series stays waived while its latest value is within this
#: relative distance of the blessed value.
BLESS_TOL = 0.1

#: Leaf-name fragments that mark a lower-is-better series (latency,
#: durations, overheads). ``fallback``/``pad_rows`` cover the
#: fit_multichip family: silent single-device fallbacks and pad overhead
#: creeping up are regressions.
#: ``quality_delta`` covers the serve_precision family: a reduced-
#: precision mode drifting further from its f32 oracle is a regression
#: even while the latency side still wins.
LOWER_BETTER = (
    "latency", "p50_", "p95_", "p99_", "_ms", "ms_", "seconds", "wall",
    "overhead", "expired", "dropped", "stalls", "deaths", "residual",
    "fallback", "pad_rows", "rel_err", "quality_delta",
)
#: Leaf-name fragments that mark a higher-is-better series (rates,
#: speedups, utilization). ``scaling`` covers the fit_multichip rows/s
#: scaling value; ``rows_per`` its per-width throughput leaves;
#: ``speedup`` also covers the fit_elastic migration-speedup value
#: (elastic resume wall vs thrown-away-work restart wall — migration
#: getting slower relative to a restart is a regression).
#: ``accuracy``/``recovery`` cover the fit_online drift family: the
#: post-refresh accuracy on the shifted stream (and how much of the
#: drift loss the refresh won back) sliding down is a regression even
#: while the re-solve wall still wins.
HIGHER_BETTER = (
    "tflops", "throughput", "per_s", "per_sec", "speedup", "img_per",
    "rows_per", "mfu", "scaling", "accuracy", "recovery",
)


def _leaf_direction(path: str, unit: Optional[str]) -> Optional[str]:
    """'lower' / 'higher' / None (unjudged) for a dotted leaf path."""
    leaf = path.lower()
    if unit:
        u = unit.lower()
        if any(k in u for k in HIGHER_BETTER):
            if leaf.endswith(("value", "vs_baseline")):
                return "higher"
        if ("ms" in u or "second" in u) and leaf.endswith("value"):
            return "lower"
    for frag in LOWER_BETTER:
        if frag in leaf:
            return "lower"
    for frag in HIGHER_BETTER:
        if frag in leaf:
            return "higher"
    return None


def _flatten(obj: Any, prefix: str = "") -> List[Tuple[str, Any]]:
    """Numeric/bool leaves of a JSON row as (dotted path, value)."""
    out: List[Tuple[str, Any]] = []
    if isinstance(obj, dict):
        for k, v in obj.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            out.extend(_flatten(v, key))
    elif isinstance(obj, bool):
        out.append((prefix, obj))
    elif isinstance(obj, (int, float)) and not (
        isinstance(obj, float) and (math.isnan(obj) or math.isinf(obj))
    ):
        out.append((prefix, obj))
    return out


class Observation:
    """One history row's reading of one series."""

    __slots__ = ("order", "value", "fingerprint", "source")

    def __init__(self, order: int, value: Any, fingerprint: dict,
                 source: str):
        self.order = order
        self.value = value
        self.fingerprint = fingerprint
        self.source = source


def _fingerprint_of(row: dict) -> Dict[str, Any]:
    """The comparability key of a bench row: backend / device count /
    host cores, from wherever this row family records them. Missing
    keys are wildcards (old rows predate the fingerprint satellite)."""
    env = row.get("env") or {}
    detail = row.get("detail") or {}
    fp = {
        "backend": row.get("backend") or env.get("backend"),
        "device_count": (
            env.get("device_count") or detail.get("devices")
            or row.get("n_devices")
        ),
        "host_cores": row.get("host_cores") or env.get("cpu_count"),
    }
    return fp


def _compatible(a: dict, b: dict) -> bool:
    """Two fingerprints are comparable when no KNOWN key disagrees."""
    for k in ("backend", "device_count", "host_cores"):
        if a.get(k) is not None and b.get(k) is not None \
                and a[k] != b[k]:
            return False
    return True


def _fp_str(fp: dict) -> str:
    return "/".join(
        f"{k}={fp.get(k)}" for k in ("backend", "device_count", "host_cores")
        if fp.get(k) is not None
    ) or "unfingerprinted"


# ---------------------------------------------------------------------------
# History loaders — one per row family
# ---------------------------------------------------------------------------


def _round_files(root: str, pattern: str) -> List[Tuple[int, str]]:
    """(round, path) pairs of numbered history files, ascending."""
    out = []
    for path in glob.glob(os.path.join(root, pattern)):
        m = re.search(r"_r(\d+)\.json$", path)
        if m:
            out.append((int(m.group(1)), path))
    return sorted(out)


def load_series(
    root: str,
) -> Tuple[Dict[str, List[Observation]], Dict[str, str]]:
    """Every series in the repo's bench history, keyed
    ``family:metric:path``, observations in chronological order — plus
    the recorded ``unit`` per series where the row family carries one
    (the bench rows' TFLOPS/ms units drive direction for the bare
    ``value`` leaf)."""
    series: Dict[str, List[Observation]] = {}
    units: Dict[str, str] = {}

    def add(family: str, metric: str, order: int, row: dict, source: str,
            unit: Optional[str] = None):
        fp = _fingerprint_of(row)
        for path, value in _flatten(row):
            # Provenance/env numbers are identity, not performance.
            if path.startswith(("env.", "keystone_env.", "detail.n",
                               "detail.d", "detail.k")):
                continue
            key = f"{family}:{metric}:{path}"
            series.setdefault(key, []).append(
                Observation(order, value, fp, source)
            )
            if unit:
                units[key] = unit

    for rnd, path in _round_files(root, "BENCH_r*.json"):
        try:
            doc = json.load(open(path))
        except (OSError, json.JSONDecodeError) as e:
            raise RuntimeError(f"unreadable history row {path}: {e}")
        parsed = doc.get("parsed")
        if not isinstance(parsed, dict):
            continue  # a round that produced no machine row gates nothing
        add("bench", str(parsed.get("metric", "unknown")), rnd, parsed,
            os.path.basename(path), unit=parsed.get("unit"))

    for rnd, path in _round_files(root, "MULTICHIP_r*.json"):
        try:
            doc = json.load(open(path))
        except (OSError, json.JSONDecodeError) as e:
            raise RuntimeError(f"unreadable history row {path}: {e}")
        if doc.get("skipped"):
            continue
        row = {k: doc.get(k) for k in ("ok", "rc", "n_devices")}
        add("multichip", "dryrun", rnd, row, os.path.basename(path))

    # JSONL histories: one fingerprinted row per line, chronological.
    # BENCH_serve.json keeps one latest row per serving metric;
    # BENCH_fit.json accumulates every `make bench-fit` / `make bench-opt`
    # / `make bench-multichip` / `make chaos-elastic` run
    # (fit_parallel_walk, fit_optimizer, fit_multichip, and fit_elastic
    # families: wall-like leaves up = regress, speedup/scaling/rows_per_s
    # down = regress — fit_elastic's value is the migration speedup,
    # resume wall vs thrown-away-work restart wall — silent-fallback
    # counts up = regress, bit_identical true->false = regress).
    for family, fname in (("serve", "BENCH_serve.json"),
                          ("fit", "BENCH_fit.json")):
        jsonl_path = os.path.join(root, fname)
        if not os.path.exists(jsonl_path):
            continue
        with open(jsonl_path) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError as e:
                    raise RuntimeError(
                        f"unreadable history row {jsonl_path}:{i + 1}: {e}"
                    )
                add(family, str(row.get("metric", "unknown")), i, row,
                    f"{fname}:{i + 1}", unit=row.get("unit"))

    return series, units


# ---------------------------------------------------------------------------
# Band fitting and judgement
# ---------------------------------------------------------------------------


def judge_series(key: str, obs: List[Observation],
                 blessed: Dict[str, dict],
                 unit: Optional[str] = None) -> Dict[str, Any]:
    """One series' verdict: ``ok`` / ``regression`` /
    ``unjudged`` / ``no_history`` / ``blessed``, with the band and the
    history that fit it."""
    latest = obs[-1]
    metric_path = key.split(":", 2)[2]
    direction = _leaf_direction(metric_path, unit)
    verdict: Dict[str, Any] = {
        "series": key,
        "latest": latest.value,
        "latest_source": latest.source,
        "fingerprint": _fp_str(latest.fingerprint),
        "direction": direction,
    }
    history = [
        o for o in obs[:-1]
        if _compatible(o.fingerprint, latest.fingerprint)
    ]
    skipped = len(obs) - 1 - len(history)
    if skipped:
        verdict["skipped_incompatible"] = skipped
    bless = blessed.get(key)
    if isinstance(latest.value, bool) or all(
        isinstance(o.value, bool) for o in obs
    ):
        # Boolean gate: true→false is a regression, everything else ok.
        # The blessed waiver applies here too (a flag held false during a
        # known outage must be blessable like any other series).
        held = any(o.value is True for o in history)
        if held and latest.value is False:
            if bless is not None and _within(latest.value,
                                             bless.get("value"), BLESS_TOL):
                verdict["status"] = "blessed"
                verdict["blessed_why"] = bless.get("why", "")
            else:
                verdict["status"] = "regression"
                verdict["reason"] = "gate flag flipped true -> false"
        else:
            verdict["status"] = "ok" if history else "no_history"
        return verdict
    if not history:
        verdict["status"] = "no_history"
        return verdict
    values = [float(o.value) for o in history]
    lo, hi = min(values), max(values)
    mean = statistics.fmean(values)
    cv = 0.0
    if len(values) >= 3 and mean:
        cv = statistics.pstdev(values) / abs(mean)
    margin = min(MAX_MARGIN, max(BASE_MARGIN, CV_K * cv))
    verdict["band"] = {
        "lo": lo, "hi": hi, "n": len(values),
        "margin": round(margin, 4),
    }
    if direction is None:
        verdict["status"] = "unjudged"
        return verdict
    if bless is not None and _within(latest.value, bless.get("value"),
                                     BLESS_TOL):
        verdict["status"] = "blessed"
        verdict["blessed_why"] = bless.get("why", "")
        return verdict
    latest_v = float(latest.value)
    if direction == "lower":
        limit = hi * (1.0 + margin) if hi >= 0 else hi * (1.0 - margin)
        if latest_v > limit:
            verdict["status"] = "regression"
            verdict["reason"] = (
                f"{latest_v:g} above noise band (history max {hi:g} "
                f"* {1 + margin:.2f} = {limit:g}, n={len(values)})"
            )
            return verdict
    else:
        limit = lo * (1.0 - margin) if lo >= 0 else lo * (1.0 + margin)
        if latest_v < limit:
            verdict["status"] = "regression"
            verdict["reason"] = (
                f"{latest_v:g} below noise band (history min {lo:g} "
                f"* {1 - margin:.2f} = {limit:g}, n={len(values)})"
            )
            return verdict
    verdict["status"] = "ok"
    return verdict


def _within(a, b, tol: float) -> bool:
    if a is None or b is None:
        return False
    a, b = float(a), float(b)
    scale = max(abs(a), abs(b), 1e-12)
    return abs(a - b) / scale <= tol


def load_bless(root: str) -> Dict[str, dict]:
    path = os.path.join(root, BLESS_FILE)
    if not os.path.exists(path):
        return {}
    doc = json.load(open(path))
    return {e["series"]: e for e in doc.get("entries", [])}


def run(root: str) -> Dict[str, Any]:
    """Judge every series; the verdict dict the CLI prints/gates on."""
    series, units = load_series(root)
    blessed = load_bless(root)
    verdicts = [
        judge_series(key, obs, blessed, unit=units.get(key))
        for key, obs in sorted(series.items())
    ]
    by_status: Dict[str, int] = {}
    for v in verdicts:
        by_status[v["status"]] = by_status.get(v["status"], 0) + 1
    regressions = [v for v in verdicts if v["status"] == "regression"]
    return {
        "metric": "bench_watch",
        "series": len(verdicts),
        "by_status": by_status,
        "regressions": regressions,
        "verdicts": verdicts,
        "ok": not regressions,
    }


def bless(root: str, series_key: str, why: str) -> dict:
    """Record the series' current latest value as intentionally accepted."""
    series, _units = load_series(root)
    if series_key not in series:
        raise KeyError(
            f"unknown series {series_key!r}; run without --bless to list"
        )
    latest = series[series_key][-1]
    path = os.path.join(root, BLESS_FILE)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    doc = {"version": 1, "entries": []}
    if os.path.exists(path):
        doc = json.load(open(path))
    entries = [e for e in doc.get("entries", [])
               if e.get("series") != series_key]
    entry = {
        "series": series_key,
        "value": latest.value,
        "source": latest.source,
        "why": why,
    }
    entries.append(entry)
    doc["entries"] = entries
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    os.replace(tmp, path)
    return entry


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="bench regression sentinel over the checked-in "
                    "bench history"
    )
    ap.add_argument("--root", default=REPO_ROOT,
                    help="repo root holding the BENCH_*/MULTICHIP_* history")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="full machine-readable verdict")
    ap.add_argument("--verbose", action="store_true",
                    help="print every series verdict, not just regressions")
    ap.add_argument("--bless", metavar="SERIES", default=None,
                    help="accept SERIES' current latest value as an "
                         "intentional change (records it in "
                         f"{BLESS_FILE})")
    ap.add_argument("--why", default="",
                    help="justification recorded with --bless")
    args = ap.parse_args(argv)

    if args.bless:
        if not args.why:
            print("bench-watch: --bless requires --why", file=sys.stderr)
            return 2
        try:
            entry = bless(args.root, args.bless, args.why)
        except (KeyError, RuntimeError) as e:
            print(f"bench-watch: {e}", file=sys.stderr)
            return 2
        print(json.dumps({"blessed": entry}))
        return 0

    try:
        result = run(args.root)
    except RuntimeError as e:
        print(f"bench-watch: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps(result))
    else:
        print(json.dumps({k: result[k] for k in
                          ("metric", "series", "by_status", "ok")}))
    shown = result["verdicts"] if args.verbose else result["regressions"]
    for v in shown:
        line = f"{v['status'].upper():<11} {v['series']}"
        if v.get("reason"):
            line += f" — {v['reason']}"
        if v.get("skipped_incompatible"):
            line += (f" [{v['skipped_incompatible']} row(s) skipped: "
                     f"fingerprint != {v['fingerprint']}]")
        print(line, file=sys.stderr)
    if result["regressions"]:
        print(
            f"bench-watch: {len(result['regressions'])} regression(s) "
            "— re-run the bench, or bless an intentional change with "
            "--bless SERIES --why '...'",
            file=sys.stderr,
        )
        return 1
    print(
        f"bench-watch: PASS ({result['series']} series, "
        f"{result['by_status']})", file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
