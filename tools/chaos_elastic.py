"""Chaos leg for the elastic mesh (ISSUE-18): kill fits mid-solve at
width 8, resume at widths 4 AND 16, demand target-width bits.

``make chaos`` proves the solvers survive injected faults at ONE mesh
width. This leg proves the other half of the recovery story: the pod
that comes back is rarely the pod that died. Three durable-state
families are seeded at an 8-device mesh and interrupted mid-solve —

- chunked stream solve (killed between checkpoints),
- BCD epoch checkpoints (killed mid-epoch-2),
- OnlineState snapshots (plain, decay, and window forgetting) —

then resumed in fresh subprocesses pinned to 4 fake devices (shrink)
and 16 (grow, wider than the seed pod — only reachable out-of-process).
Each resume must migrate (counted in the ``elastic`` metrics family),
and the final weights must be BIT-IDENTICAL to an uninterrupted fit at
the target width: the canonical gram fold (``config.gram_fold_blocks``)
makes the accumulator sums width-invariant, so this is an equality
gate, not a tolerance check. Fresh fits must migrate NOTHING — zero
silent migrations.

The whole run executes under the chaos fault plan
(``KEYSTONE_FAULTS=io:0.05,oom:1``) inherited from the environment, so
migration machinery is exercised with I/O faults landing mid-restore.

The result row APPENDS to ``--out`` (BENCH_fit.json) as the
``fit_elastic`` family: value = thrown-away-work restart wall /
elastic resume wall (HIGHER_BETTER speedup; bench_watch also regresses
on any ``bit_identical_*`` flip).

Usage:
    python tools/chaos_elastic.py [--quick] [--out BENCH_fit.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SEED_WIDTH = 8
TARGET_WIDTHS = (4, 16)  # shrink AND grow past the seed pod

D, K = 12, 3


class Kill(Exception):
    """The injected mid-solve pod death."""


def _sizes(quick: bool):
    """(stream rows, stream chunks, bcd rows, bcd dim, online rows)."""
    if quick:
        return 72, 6, 68, 16, 64
    return 288, 6, 260, 32, 256


def _stream_data(n, chunks):
    import numpy as np

    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, D)).astype(np.float32)
    Y = rng.normal(size=(n, K)).astype(np.float32)
    rows = n // chunks

    def it():
        for i in range(chunks):
            yield X[i * rows:(i + 1) * rows], Y[i * rows:(i + 1) * rows]

    return it


def _bcd_data(n, d):
    import numpy as np

    rng = np.random.default_rng(1)
    return (rng.normal(size=(n, d)).astype(np.float32),
            rng.normal(size=(n, K)).astype(np.float32))


def _online_splits(n):
    import numpy as np

    rng = np.random.default_rng(2)
    X = rng.normal(size=(n, D)).astype(np.float32)
    Y = rng.normal(size=(n, K)).astype(np.float32)
    q = n // 4
    return [(X[s:e], Y[s:e])
            for s, e in [(0, q), (q, 2 * q), (2 * q, 3 * q), (3 * q, n)]]


_ONLINE_MODES = (("plain", {}), ("decay", {"decay": 0.5}),
                 ("window", {"window": 2}))


# ---------------------------------------------------------------------------
# Workers (separate processes: XLA fixes the fake-device count at init)
# ---------------------------------------------------------------------------


def _worker_seed(root: str, quick: bool) -> None:
    """Width-8 pod: do partial work per family, checkpoint, 'die'."""
    from keystone_tpu.linalg import solve_least_squares_chunked
    import keystone_tpu.linalg.bcd as bcd_mod
    from keystone_tpu.linalg.row_matrix import RowMatrix
    from keystone_tpu.nodes.learning.linear_mapper import LinearMapEstimator

    sn, sc, bn, bd, on = _sizes(quick)

    # Stream: checkpoints land every 2 chunks; the kill strikes at
    # chunk 4, so chunks 0-3 survive and 4+ are lost work.
    it = _stream_data(sn, sc)

    def killed():
        for i, batch in enumerate(it()):
            if i == 4:
                raise Kill()
            yield batch

    try:
        solve_least_squares_chunked(
            killed(), lam=0.1,
            checkpoint_dir=os.path.join(root, "stream"), checkpoint_every=2,
        )
    except Kill:
        pass

    # BCD: interrupt a real num_iters=2 run right after the epoch-1
    # save — seeding with num_iters=1 instead would flip the auto
    # cache_grams policy and the resumed bits could never match the
    # uninterrupted reference.
    Xh, Yh = _bcd_data(bn, bd)
    real_save = bcd_mod._save_epoch

    def killing_save(*a, **k):
        real_save(*a, **k)
        raise Kill()

    bcd_mod._save_epoch = killing_save
    try:
        bcd_mod.block_coordinate_descent(
            RowMatrix.from_array(Xh), RowMatrix.from_array(Yh),
            block_size=8, num_iters=2, lam=1e-3,
            checkpoint_dir=os.path.join(root, "bcd"),
        )
    except Kill:
        pass
    finally:
        bcd_mod._save_epoch = real_save
    bcd_mod.wait_for_checkpoints(os.path.join(root, "bcd"))

    # Online: two of four batches folded, snapshot saved, per mode.
    est = LinearMapEstimator(lam=1e-3)
    splits = _online_splits(on)
    for mode, kw in _ONLINE_MODES:
        st = None
        for bx, by in splits[:2]:
            st = est.partial_fit(bx, by, state=st, **kw)
        st.save(os.path.join(root, f"online_{mode}"))

    print("CHAOS_ROW " + json.dumps({"seeded": True}), flush=True)


def _worker_resume(root: str, quick: bool, width: int) -> None:
    """Target-width pod: resume every family (timed), refit fresh
    (timed), gate on bit-identity and on counted-vs-silent migrations."""
    import numpy as np

    from keystone_tpu.linalg import solve_least_squares_chunked
    from keystone_tpu.linalg.bcd import (
        assemble_blocks,
        block_coordinate_descent,
    )
    from keystone_tpu.linalg.row_matrix import RowMatrix
    from keystone_tpu.nodes.learning.linear_mapper import LinearMapEstimator
    from keystone_tpu.utils.metrics import elastic_counters
    from keystone_tpu.workflow.online import OnlineState

    sn, sc, bn, bd, on = _sizes(quick)
    it = _stream_data(sn, sc)
    Xh, Yh = _bcd_data(bn, bd)
    splits = _online_splits(on)
    est = LinearMapEstimator(lam=1e-3)

    resumed = {}
    t0 = time.perf_counter()
    resumed["stream"] = np.asarray(solve_least_squares_chunked(
        it(), lam=0.1,
        checkpoint_dir=os.path.join(root, "stream"), checkpoint_every=2,
    ))
    Wr, _ = block_coordinate_descent(
        RowMatrix.from_array(Xh), RowMatrix.from_array(Yh),
        block_size=8, num_iters=2, lam=1e-3,
        checkpoint_dir=os.path.join(root, "bcd"),
    )
    resumed["bcd"] = np.asarray(assemble_blocks(Wr))
    for mode, kw in _ONLINE_MODES:
        st = OnlineState.load(os.path.join(root, f"online_{mode}"))
        assert st is not None, f"online_{mode} snapshot failed to load"
        for bx, by in splits[2:]:
            st = est.partial_fit(bx, by, state=st, **kw)
        m = est.solve_online(st)
        resumed[f"online_{mode}"] = np.concatenate(
            [np.asarray(m.W).ravel(), np.asarray(m.b).ravel()])
    resume_wall = time.perf_counter() - t0
    migrations = elastic_counters.get("states_migrated")

    fresh = {}
    t0 = time.perf_counter()
    fresh["stream"] = np.asarray(solve_least_squares_chunked(it(), lam=0.1))
    Wf, _ = block_coordinate_descent(
        RowMatrix.from_array(Xh), RowMatrix.from_array(Yh),
        block_size=8, num_iters=2, lam=1e-3,
    )
    fresh["bcd"] = np.asarray(assemble_blocks(Wf))
    for mode, kw in _ONLINE_MODES:
        st = None
        for bx, by in splits:
            st = est.partial_fit(bx, by, state=st, **kw)
        m = est.solve_online(st)
        fresh[f"online_{mode}"] = np.concatenate(
            [np.asarray(m.W).ravel(), np.asarray(m.b).ravel()])
    restart_wall = time.perf_counter() - t0
    fresh_migrations = elastic_counters.get("states_migrated") - migrations

    families = {
        fam: bool(np.array_equal(resumed[fam], fresh[fam]))
        for fam in fresh
    }
    print("CHAOS_ROW " + json.dumps({
        "width": width,
        "bit_identical": all(families.values()),
        "families": families,
        "migrations": migrations,
        "fresh_migrations": fresh_migrations,
        "resume_wall_s": round(resume_wall, 4),
        "restart_wall_s": round(restart_wall, 4),
    }), flush=True)


# ---------------------------------------------------------------------------
# Parent orchestration
# ---------------------------------------------------------------------------


def _spawn(role: str, width: int, root: str, quick: bool) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={width}"
    # Workers run as a script (sys.path[0] = tools/); the package lives
    # at the repo root.
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, os.path.abspath(__file__),
           "--worker", role, "--width", str(width), "--root", root]
    if quick:
        cmd.append("--quick")
    proc = subprocess.run(
        cmd, env=env, cwd=REPO, capture_output=True, text=True, timeout=480,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"{role}@{width} worker failed rc={proc.returncode}\n"
            f"stdout:{proc.stdout[-1000:]}\nstderr:{proc.stderr[-2000:]}"
        )
    for line in proc.stdout.splitlines():
        if line.startswith("CHAOS_ROW "):
            return json.loads(line[len("CHAOS_ROW "):])
    raise RuntimeError(
        f"{role}@{width} worker printed no row\nstdout:{proc.stdout[-1000:]}"
    )


def run_chaos(quick: bool) -> dict:
    work = tempfile.mkdtemp(prefix="chaos_elastic_")
    try:
        seed_root = os.path.join(work, "seed")
        os.makedirs(seed_root)
        _spawn("seed", SEED_WIDTH, seed_root, quick)
        per_width = {}
        for width in TARGET_WIDTHS:
            # Each target resumes from its own COPY of the dead pod's
            # checkpoints: a resumed run rewrites the directory at the
            # new width, which must not contaminate the other target.
            wroot = os.path.join(work, f"w{width}")
            shutil.copytree(seed_root, wroot)
            per_width[width] = _spawn("resume", width, wroot, quick)
    finally:
        shutil.rmtree(work, ignore_errors=True)

    shrink, grow = per_width[TARGET_WIDTHS[0]], per_width[TARGET_WIDTHS[1]]
    resume_wall = shrink["resume_wall_s"] + grow["resume_wall_s"]
    restart_wall = shrink["restart_wall_s"] + grow["restart_wall_s"]
    migrations = shrink["migrations"] + grow["migrations"]
    fresh_migrations = (
        shrink["fresh_migrations"] + grow["fresh_migrations"]
    )
    speedup = restart_wall / resume_wall if resume_wall > 0 else float("inf")

    import jax

    from keystone_tpu.utils.metrics import environment_fingerprint

    sn, sc, bn, bd, on = _sizes(quick)
    row = {
        "metric": "fit_elastic",
        "value": round(speedup, 3),
        "unit": ("x migration speedup "
                 "(thrown-away-work restart wall / elastic resume wall)"),
        "backend": jax.default_backend(),
        "host_cores": os.cpu_count() or 1,
        "env": environment_fingerprint(),
        "detail": {
            "seed_width": SEED_WIDTH,
            "target_widths": list(TARGET_WIDTHS),
            "stream_rows": sn,
            "bcd_rows": bn,
            "online_rows": on,
            "bit_identical_shrink": shrink["bit_identical"],
            "bit_identical_grow": grow["bit_identical"],
            "families_shrink": shrink["families"],
            "families_grow": grow["families"],
            "migrations": migrations,
            "fresh_migrations": fresh_migrations,
            "resume_wall_s": round(resume_wall, 4),
            "restart_wall_s": round(restart_wall, 4),
        },
    }
    # The speedup is informational on CPU (compile noise dominates the
    # tiny chaos problems); the GATES are bit-identity both directions,
    # every resume migrated, and zero silent migrations on fresh fits.
    row["ok"] = bool(
        shrink["bit_identical"] and grow["bit_identical"]
        and migrations >= 2 and fresh_migrations == 0
    )
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Kill fits at width 8, resume at widths 4 and 16, "
                    "gate on target-width bit-identity.")
    ap.add_argument("--quick", action="store_true",
                    help="tiny problem sizes (harness validation)")
    ap.add_argument("--out", default=None,
                    help="append the result row to this JSONL file")
    ap.add_argument("--worker", choices=["seed", "resume"], default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--width", type=int, default=SEED_WIDTH,
                    help=argparse.SUPPRESS)
    ap.add_argument("--root", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.worker == "seed":
        _worker_seed(args.root, args.quick)
        return 0
    if args.worker == "resume":
        _worker_resume(args.root, args.quick, args.width)
        return 0

    row = run_chaos(args.quick)
    if args.out:
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
    print(json.dumps(row), flush=True)
    return 0 if row["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
