#!/usr/bin/env python3
"""keystone-lint: the codebase invariant checker (Layer 2, stdlib ast).

Encodes the concurrency and hot-path disciplines this repo already bled
for — lock-guarded serving state (PR 2/5), the resolve-once rule for
``active_plan()``/``active_tracer()`` (PR 3/4), env-read-once via
``config`` — as mechanical checks, so they are enforced by a tool
instead of reviewer memory. Pure stdlib (``ast`` + ``json``): no jax, no
keystone_tpu import, so it runs anywhere in milliseconds.

Rule catalog (KL = Keystone Lint):

- ``KL001 lock-discipline`` — in a thread-spawning or lock-holding
  class, an instance attribute mutated from >= 2 thread entry points
  must be written under ``with self._lock``/``self._cv``/... (or from a
  ``*_locked`` method, the repo's caller-holds-the-lock convention).
- ``KL002 lock-order`` — lock-acquisition-order cycles across
  ``Lock``/``Condition`` sites (A under B in one method, B under A in
  another), plus nested acquisition of one non-reentrant lock.
  Conditions constructed over a shared Lock alias to it.
- ``KL003 env-read`` — ``os.environ``/``os.getenv`` outside config.py:
  env knobs are read once at config import, never on hot paths.
- ``KL004 resolve-once`` — ``active_plan()``/``active_tracer()``/
  ``active_profile()`` called inside a loop body: resolve once per
  stream/solve/service/execution walk.
- ``KL005 wall-clock-timing`` — ``time.time()`` in library code: spans
  and latencies use ``perf_counter``; wall-clock survivors carry a tag.
- ``KL006 broad-except`` — an ``except Exception/BaseException`` must
  re-raise, route through ``utils/reliability`` classification
  (``is_transient``/``is_oom``), or carry a ``# lint: broad-ok`` tag
  with a reason.
- ``KL007 dispatch-host-sync`` — blocking host syncs
  (``block_until_ready``, ``device_get``, ``np.asarray``) inside the
  serving dispatch path (``submit``/``_loop``/``_dispatch``/...): the
  dispatcher must never wait on a device.
- ``KL008 lost-wakeup`` — ``notify()`` (not ``notify_all``) on a
  condition that >= 2 distinct thread-target methods wait on: one
  waiter can consume a wakeup meant for another (the PR-5 serving bug).

Suppression: ``# lint: ok(KLnnn) reason`` on the flagged line (or the
line above); ``# lint: broad-ok reason`` is the KL006 spelling. Findings
neither fixed nor tagged live in the checked-in baseline
(tools/lint_baseline.json, each entry with a justification) — the gate
is zero-tolerance on findings NOT in the baseline, so the shipped tree
lints clean and new violations can never ride in silently.

Usage:
    python tools/keystone_lint.py [paths...] [--baseline FILE]
        [--write-baseline] [--json] [--no-baseline]

Exit status: 0 = no new findings, 1 = new findings (listed), 2 = usage.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from typing import Dict, List, Optional, Set, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_PATHS = ["keystone_tpu"]
DEFAULT_BASELINE = os.path.join("tools", "lint_baseline.json")

#: Files whose env reads ARE the config layer (KL003 exempt).
ENV_ALLOWED = {"keystone_tpu/config.py"}

#: Method names that form the serving dispatch path (KL007): nothing in
#: them may block on a device transfer.
DISPATCH_METHODS = {"submit", "_loop", "_dispatch", "_pick_slot_locked",
                    "_ensure_worker_locked"}

#: Method names that are ALWAYS treated as thread-entry roots for the
#: concurrency rules, even when no ``Thread(target=self.X)`` spawn is
#: statically visible in the class (spawned via a helper, a registry, or
#: a future refactor). The observability threads are registered here by
#: name so lock discipline covers them from day one — a watchdog that
#: mutates service state outside the lock must be a finding, not a blind
#: spot behind an indirect spawn.
KNOWN_THREAD_TARGETS = {"_watchdog_loop", "_watch_loop", "_solve_watch_loop",
                        "_run_node_worker",
                        # workflow/daemon.py ServingDaemon: the socket
                        # ingress accept thread, its per-connection
                        # workers, and the hot-swap worker.
                        "_accept_loop", "_serve_conn", "_swap_loop",
                        # workflow/online.py OnlineTrainer: the cadence
                        # refresh worker (re-solve + artifact + swap).
                        "_refresh_loop",
                        # utils/telemetry.py TelemetryLog: the durable
                        # journey-export writer (drains the bounded
                        # queue to rotated JSONL segments off the hot
                        # path).
                        "_writer_loop",
                        # workflow/daemon.py ServingDaemon: the capacity
                        # re-plan worker (traffic-aware autoscaling off
                        # the learned capacity model).
                        "_replan_loop"}
HOST_SYNC_CALLS = {"block_until_ready", "device_get", "asarray", "array"}

#: Mutating method names treated as writes for KL001 (deque/list/set/dict
#: mutation on a self attribute).
MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
            "pop", "popleft", "remove", "clear", "add", "discard",
            "update", "setdefault"}

AST_RULES: Dict[str, str] = {
    "KL000": "file does not parse (syntax error)",
    "KL001": "shared attribute written outside the instance lock",
    "KL002": "lock-acquisition-order cycle / nested non-reentrant lock",
    "KL003": "os.environ read outside config.py",
    "KL004": "active_plan()/active_tracer() resolved inside a loop",
    "KL005": "time.time() used in library code (use perf_counter)",
    "KL006": "broad except without re-raise/classification/broad-ok tag",
    "KL007": "blocking host sync on the serving dispatch path",
    "KL008": "notify() on a condition waited on by >= 2 threads",
}

SEVERITY = {
    "KL000": "error",
    "KL001": "error", "KL002": "error", "KL003": "warning",
    "KL004": "warning", "KL005": "warning", "KL006": "warning",
    "KL007": "error", "KL008": "error",
}


class Finding:
    __slots__ = ("rule", "path", "line", "message", "hint")

    def __init__(self, rule: str, path: str, line: int, message: str,
                 hint: str = ""):
        self.rule = rule
        self.path = path
        self.line = line
        self.message = message
        self.hint = hint

    @property
    def severity(self) -> str:
        return SEVERITY[self.rule]

    def key(self, source_lines: List[str]) -> str:
        """Line-number-independent identity: rule | path | stripped source
        text of the flagged line — stable across unrelated edits above."""
        text = ""
        if 1 <= self.line <= len(source_lines):
            text = source_lines[self.line - 1].strip()
        return f"{self.rule}|{self.path}|{text}"

    def as_dict(self) -> dict:
        return {
            "rule": self.rule, "severity": self.severity, "path": self.path,
            "line": self.line, "message": self.message, "hint": self.hint,
        }


# ---------------------------------------------------------------------------
# Shared AST helpers
# ---------------------------------------------------------------------------


def _dotted(node: ast.AST) -> Optional[str]:
    """'self._lock' / 'os.environ' textual form of a Name/Attribute chain
    (None for anything fancier)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _lock_key(node: ast.AST) -> Optional[str]:
    """Normalized lock identity of a with-item context expression:
    'self._lock', or 'self._ccvs[]' for a subscripted lock pool."""
    if isinstance(node, ast.Subscript):
        base = _dotted(node.value)
        return f"{base}[]" if base else None
    return _dotted(node)


def _suppressed(lines: List[str], lineno: int, rule: str) -> bool:
    """True when the flagged line (or the one above it) carries a
    ``# lint: ok(RULE)`` tag — or ``# lint: broad-ok`` for KL006."""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            text = lines[ln - 1]
            if f"lint: ok({rule})" in text:
                return True
            if rule == "KL006" and "lint: broad-ok" in text:
                return True
    return False


def _self_attr_target(node: ast.AST) -> Optional[str]:
    """Attribute name when ``node`` is a store on self: ``self.x``,
    ``self.x[i]`` — the instance state KL001 guards."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    if isinstance(node, ast.Subscript):
        return _self_attr_target(node.value)
    if isinstance(node, (ast.Tuple, ast.List)):
        return None  # handled element-wise by the caller
    return None


# ---------------------------------------------------------------------------
# KL001 / KL002 / KL008 — the concurrency rules (per class)
# ---------------------------------------------------------------------------


class _MethodFacts:
    """Everything the concurrency rules need from one method body."""

    def __init__(self, name: str):
        self.name = name
        # (attr, locked, lineno, kind) — kind 'assign' | 'mutate'
        self.writes: List[Tuple[str, bool, int, str]] = []
        self.calls: Set[str] = set()          # self-method names called
        self.thread_targets: Set[str] = set() # methods passed to Thread()
        self.wait_locks: Set[str] = set()     # lock keys .wait()ed on
        # (lock key, lineno) .notify() sites (notify_all is always safe)
        self.notify_sites: List[Tuple[str, int]] = []
        # (outer_key, inner_key, lineno) nested with-acquisitions
        self.nestings: List[Tuple[str, str, int]] = []
        self.spawns_thread = False


def _is_lockish(expr: ast.AST) -> bool:
    """Does this expression construct a Lock/RLock/Condition (directly or
    inside a comprehension/list)?"""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call):
            callee = _dotted(sub.func) or ""
            if callee.split(".")[-1] in ("Lock", "RLock", "Condition",
                                         "Semaphore", "BoundedSemaphore"):
                return True
    return False


def _condition_alias(expr: ast.AST) -> Optional[str]:
    """For ``threading.Condition(self._lock)`` (possibly inside a list
    comprehension), the dotted name of the shared underlying lock."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call):
            callee = _dotted(sub.func) or ""
            if callee.split(".")[-1] == "Condition" and sub.args:
                return _dotted(sub.args[0])
    return None


def _collect_method(fn: ast.FunctionDef, lock_attrs: Set[str]) -> _MethodFacts:
    facts = _MethodFacts(fn.name)

    def lock_of(expr: ast.AST) -> Optional[str]:
        key = _lock_key(expr)
        if key is None or not key.startswith("self."):
            return None
        attr = key[len("self."):].rstrip("[]")
        return key if attr in lock_attrs else None

    def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, ast.With):
            acquired = []
            for item in node.items:
                key = lock_of(item.context_expr)
                if key is not None:
                    for h in held + tuple(acquired):
                        facts.nestings.append((h, key, node.lineno))
                    acquired.append(key)
            inner = held + tuple(acquired)
            for child in node.body:
                visit(child, inner)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            flat = []
            for t in targets:
                flat.extend(t.elts if isinstance(t, (ast.Tuple, ast.List))
                            else [t])
            for t in flat:
                attr = _self_attr_target(t)
                if attr is not None:
                    facts.writes.append(
                        (attr, bool(held), node.lineno, "assign")
                    )
        if isinstance(node, ast.Call):
            callee = _dotted(node.func) or ""
            if callee.split(".")[-1] == "Thread":
                facts.spawns_thread = True
                for kw in node.keywords:
                    if kw.arg == "target":
                        tgt = _dotted(kw.value) or ""
                        if tgt.startswith("self."):
                            facts.thread_targets.add(tgt[len("self."):])
            if isinstance(node.func, ast.Attribute):
                recv = node.func.value
                meth = node.func.attr
                recv_txt = _dotted(recv)
                if recv_txt == "self":
                    facts.calls.add(meth)
                # deque/list/dict mutation on a self attribute
                attr = _self_attr_target(recv)
                if attr is not None and meth in MUTATORS \
                        and attr not in lock_attrs:
                    facts.writes.append(
                        (attr, bool(held), node.lineno, "mutate")
                    )
                # condition wait/notify sites (self._cv.wait(), incl.
                # subscripted pools self._ccvs[r].wait())
                lk = lock_of(recv)
                if lk is not None:
                    if meth == "wait":
                        facts.wait_locks.add(lk)
                    elif meth == "notify":
                        facts.notify_sites.append((lk, node.lineno))
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                # Nested defs/lambdas: their bodies run later, in unknown
                # lock context — analyze conservatively as unlocked.
                for sub in (child.body if isinstance(child.body, list)
                            else [child.body]):
                    visit(sub, ())
                continue
            visit(child, held)

    for stmt in fn.body:
        visit(stmt, ())
    return facts


def _class_lock_attrs(cls: ast.ClassDef) -> Tuple[Set[str], Dict[str, str]]:
    """Lock-ish instance attributes assigned in __init__ (or class body),
    plus condition -> underlying-lock aliases."""
    locks: Set[str] = set()
    aliases: Dict[str, str] = {}
    for fn in cls.body:
        if not isinstance(fn, ast.FunctionDef):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                attr = _self_attr_target(t)
                if attr is None:
                    continue
                if _is_lockish(node.value):
                    locks.add(attr)
                    shared = _condition_alias(node.value)
                    if shared and shared.startswith("self."):
                        aliases[f"self.{attr}"] = shared
    return locks, aliases


def _check_class(cls: ast.ClassDef, path: str, lines: List[str],
                 findings: List[Finding]) -> None:
    lock_attrs, aliases = _class_lock_attrs(cls)
    methods = {
        fn.name: _collect_method(fn, lock_attrs)
        for fn in cls.body if isinstance(fn, ast.FunctionDef)
    }
    if not methods:
        return
    spawns = any(m.spawns_thread for m in methods.values())
    thread_targets = set().union(
        *(m.thread_targets for m in methods.values())
    ) & set(methods)
    # Registered roots: these method names are thread targets by
    # contract even when the spawn isn't statically visible here.
    known = KNOWN_THREAD_TARGETS & set(methods)
    if known:
        thread_targets |= known
        spawns = True
    if not lock_attrs and not spawns:
        return  # plain class: no concurrency contract to check

    def is_public(name: str) -> bool:
        return not name.startswith("_") or (
            name.startswith("__") and name.endswith("__")
            and name != "__init__"
        )

    # Entry roots: each thread-target method is its own root. For a
    # thread-spawning class the public surface is ONE client root (the
    # single-consumer pattern: __next__/close belong to one caller); for
    # a lock-holding class with no threads of its own (CompiledPipeline:
    # shared BY other threads), every public method is a separate root.
    roots: Dict[str, Set[str]] = {}
    if spawns:
        client = {n for n in methods if is_public(n) and n != "__init__"
                  and n not in thread_targets}
        if client:
            roots["<client>"] = client
        for t in thread_targets:
            roots[t] = {t}
    else:
        for n in methods:
            if is_public(n) and n != "__init__" and n not in thread_targets:
                roots[n] = {n}
        for t in thread_targets:
            roots[t] = {t}

    # Reachability over the self-call graph.
    reach: Dict[str, Set[str]] = {}
    for root, seeds in roots.items():
        seen: Set[str] = set()
        stack = list(seeds)
        while stack:
            m = stack.pop()
            if m in seen or m not in methods:
                continue
            seen.add(m)
            stack.extend(methods[m].calls)
        reach[root] = seen

    # attr -> set of roots whose reachable methods write it.
    attr_roots: Dict[str, Set[str]] = {}
    for root, rset in reach.items():
        for mname in rset:
            if mname == "__init__":
                continue
            for attr, _locked, _ln, _k in methods[mname].writes:
                attr_roots.setdefault(attr, set()).add(root)

    # -- KL001 -------------------------------------------------------------
    for mname, facts in methods.items():
        if mname == "__init__" or mname.endswith("_locked"):
            continue  # setup / caller-holds-the-lock convention
        for attr, locked, lineno, kind in facts.writes:
            if locked or attr in lock_attrs:
                continue
            sharers = attr_roots.get(attr, set())
            if len(sharers) < 2:
                continue
            if _suppressed(lines, lineno, "KL001"):
                continue
            verb = "mutates" if kind == "mutate" else "writes"
            findings.append(Finding(
                "KL001", path, lineno,
                f"{cls.name}.{mname} {verb} self.{attr} outside the lock; "
                f"the attribute is written from entry points "
                f"{sorted(sharers)}",
                hint="wrap in `with self._lock:` (or move into a *_locked "
                     "helper whose callers hold it)",
            ))

    # -- KL002 -------------------------------------------------------------
    def norm(key: str) -> str:
        return aliases.get(key.rstrip("[]"), key)

    edges: Dict[Tuple[str, str], int] = {}
    for facts in methods.values():
        for outer, inner, lineno in facts.nestings:
            o, i = norm(outer), norm(inner)
            if o == i:
                if not _suppressed(lines, lineno, "KL002"):
                    findings.append(Finding(
                        "KL002", path, lineno,
                        f"{cls.name}: nested acquisition of non-reentrant "
                        f"{outer} (Condition/Lock share one underlying "
                        "lock) — self-deadlock",
                        hint="release before re-acquiring, or restructure "
                             "so one method owns the lock",
                    ))
                continue
            edges.setdefault((o, i), lineno)
    # Cycle detection over the acquisition-order digraph.
    graph: Dict[str, Set[str]] = {}
    for (o, i) in edges:
        graph.setdefault(o, set()).add(i)
    state: Dict[str, int] = {}

    def dfs(n: str, trail: List[str]) -> Optional[List[str]]:
        state[n] = 1
        for nxt in graph.get(n, ()):
            if state.get(nxt) == 1:
                return trail + [n, nxt]
            if state.get(nxt, 0) == 0:
                cyc = dfs(nxt, trail + [n])
                if cyc:
                    return cyc
        state[n] = 2
        return None

    for n in list(graph):
        if state.get(n, 0) == 0:
            cyc = dfs(n, [])
            if cyc:
                a, b = cyc[-2], cyc[-1]
                lineno = edges.get((a, b)) or next(iter(edges.values()))
                if not _suppressed(lines, lineno, "KL002"):
                    findings.append(Finding(
                        "KL002", path, lineno,
                        f"{cls.name}: lock-acquisition-order cycle "
                        f"{' -> '.join(cyc[cyc.index(b):] + [b])} — two "
                        "threads taking the locks in opposite orders "
                        "deadlock",
                        hint="impose one global acquisition order",
                    ))
                break

    # -- KL008 -------------------------------------------------------------
    # Deliberately keyed on CONDITION identity, not the norm()-aliased
    # underlying lock: distinct Conditions sharing one Lock have separate
    # wait-sets — per-waiter conditions over a shared lock are the FIX
    # for lost wakeups, and must lint clean.
    if thread_targets:
        waiters: Dict[str, Set[str]] = {}
        for root in thread_targets:
            for mname in reach.get(root, ()):
                for lk in methods[mname].wait_locks:
                    waiters.setdefault(lk, set()).add(root)
        for facts in methods.values():
            for lk, lineno in facts.notify_sites:
                key = lk
                if len(waiters.get(key, ())) >= 2:
                    if not _suppressed(lines, lineno, "KL008"):
                        findings.append(Finding(
                            "KL008", path, lineno,
                            f"{cls.name}.{facts.name} calls {lk}.notify() "
                            f"but threads {sorted(waiters[key])} both wait "
                            "on it: one waiter can consume a wakeup meant "
                            "for the other (lost wakeup, the PR-5 serving "
                            "bug)",
                            hint="use notify_all(), or give each waiter "
                                 "class its own Condition over the shared "
                                 "lock",
                        ))


# ---------------------------------------------------------------------------
# File-scope rules (KL003-KL007)
# ---------------------------------------------------------------------------


def _check_file_rules(tree: ast.Module, path: str, lines: List[str],
                      findings: List[Finding]) -> None:
    env_exempt = path in ENV_ALLOWED

    class V(ast.NodeVisitor):
        def __init__(self):
            self.loop_depth = 0
            self.func_stack: List[str] = []

        # -- loops (KL004 scope) ------------------------------------------
        def visit_For(self, node):
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        visit_While = visit_For
        visit_AsyncFor = visit_For

        def visit_FunctionDef(self, node):
            self.func_stack.append(node.name)
            # A nested def inside a loop runs later: reset loop context.
            saved, self.loop_depth = self.loop_depth, 0
            self.generic_visit(node)
            self.loop_depth = saved
            self.func_stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        # -- KL003 / KL004 / KL005 / KL007 ---------------------------------
        def visit_Attribute(self, node):
            if not env_exempt and _dotted(node) == "os.environ":
                if not _suppressed(lines, node.lineno, "KL003"):
                    findings.append(Finding(
                        "KL003", path, node.lineno,
                        "os.environ read outside config.py: env knobs are "
                        "resolved once at config import, not on demand",
                        hint="add a config field / helper in config.py and "
                             "read that",
                    ))
            self.generic_visit(node)

        def visit_Call(self, node):
            callee = _dotted(node.func) or ""
            leaf = callee.split(".")[-1]
            if not env_exempt and callee in ("os.getenv",):
                if not _suppressed(lines, node.lineno, "KL003"):
                    findings.append(Finding(
                        "KL003", path, node.lineno,
                        "os.getenv outside config.py",
                        hint="route through config.py",
                    ))
            if leaf in ("active_plan", "active_tracer",
                        "active_profile") and self.loop_depth:
                if not _suppressed(lines, node.lineno, "KL004"):
                    findings.append(Finding(
                        "KL004", path, node.lineno,
                        f"{leaf}() resolved inside a loop body: the "
                        "resolve-once discipline keeps the disabled "
                        "harness at one None check per stream",
                        hint="hoist the call above the loop (once per "
                             "stream/solve/service)",
                    ))
            if callee == "time.time":
                if not _suppressed(lines, node.lineno, "KL005"):
                    findings.append(Finding(
                        "KL005", path, node.lineno,
                        "time.time() in library code: span/latency timing "
                        "must use a monotonic clock",
                        hint="time.perf_counter() for durations; tag "
                             "`# lint: ok(KL005) <why>` for real "
                             "wall-clock uses (file mtimes)",
                    ))
            if (
                self.func_stack
                and self.func_stack[-1] in DISPATCH_METHODS
                and leaf in HOST_SYNC_CALLS
            ):
                if not _suppressed(lines, node.lineno, "KL007"):
                    findings.append(Finding(
                        "KL007", path, node.lineno,
                        f"{callee or leaf}() inside dispatch-path method "
                        f"{self.func_stack[-1]}: a blocking host sync "
                        "stalls every queued request behind this one",
                        hint="materialize on the completion side "
                             "(completer threads / _AsyncResult.wait)",
                    ))
            self.generic_visit(node)

    V().visit(tree)

    # -- KL006: broad except handlers --------------------------------------
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler) or node.type is None:
            continue
        names = []
        types = (node.type.elts if isinstance(node.type, ast.Tuple)
                 else [node.type])
        for t in types:
            d = _dotted(t)
            if d:
                names.append(d.split(".")[-1])
        if not ({"Exception", "BaseException"} & set(names)):
            continue
        body_calls = {
            (_dotted(c.func) or "").split(".")[-1]
            for c in ast.walk(node) if isinstance(c, ast.Call)
        }
        reraises = any(isinstance(s, ast.Raise) for s in ast.walk(node))
        classifies = bool(body_calls & {"is_transient", "is_oom"})
        if reraises or classifies:
            continue
        if _suppressed(lines, node.lineno, "KL006"):
            continue
        findings.append(Finding(
            "KL006", path, node.lineno,
            "broad `except Exception` neither re-raises, classifies via "
            "utils/reliability (is_transient/is_oom), nor carries a "
            "`# lint: broad-ok` tag",
            hint="narrow to the known failure type, or tag with the "
                 "reason the catch-all is deliberate",
        ))


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def scan_source(source: str, relpath: str) -> List[Finding]:
    lines = source.splitlines()
    try:
        tree = ast.parse(source, filename=relpath)
    except SyntaxError as e:
        return [Finding("KL000", relpath, e.lineno or 1,
                        f"syntax error: {e.msg}")]
    findings: List[Finding] = []
    _check_file_rules(tree, relpath, lines, findings)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            _check_class(node, relpath, lines, findings)
    return findings


def iter_py_files(paths: List[str], root: str) -> List[Tuple[str, str]]:
    out = []
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(ap):
            # A misspelled/renamed path must FAIL, not pass vacuously —
            # a zero-tolerance gate that scans nothing gates nothing.
            raise FileNotFoundError(f"lint path does not exist: {ap}")
        if os.path.isfile(ap):
            out.append((ap, os.path.relpath(ap, root)))
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    fp = os.path.join(dirpath, fn)
                    out.append((fp, os.path.relpath(fp, root)))
    return sorted(out)


def scan(paths: List[str], root: str = REPO_ROOT):
    """Scan ``paths`` (files or directories, relative to ``root``).
    Returns (findings, keys) where keys[i] is findings[i]'s baseline
    identity."""
    findings: List[Finding] = []
    keys: List[str] = []
    for abspath, relpath in iter_py_files(paths, root):
        with open(abspath, "r", encoding="utf-8") as f:
            source = f.read()
        lines = source.splitlines()
        for fd in scan_source(source, relpath):
            findings.append(fd)
            keys.append(fd.key(lines))
    return findings, keys


def load_baseline(path: str) -> Dict[str, dict]:
    """Baseline entries keyed by finding identity (count-aware callers
    use a multiset; identical keys may repeat in `entries`)."""
    with open(path) as f:
        doc = json.load(f)
    return doc


def new_findings(findings: List[Finding], keys: List[str],
                 baseline: Optional[dict]):
    """Findings whose identity is not covered by the baseline multiset."""
    budget: Dict[str, int] = {}
    for e in (baseline or {}).get("entries", []):
        budget[e["key"]] = budget.get(e["key"], 0) + 1
    fresh = []
    for fd, key in zip(findings, keys):
        if budget.get(key, 0) > 0:
            budget[key] -= 1
        else:
            fresh.append(fd)
    return fresh


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="keystone-lint: codebase invariant checker (AST layer)"
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to scan (default: {DEFAULT_PATHS})")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON of accepted pre-existing findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings as the new baseline")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--root", default=REPO_ROOT, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    paths = args.paths or DEFAULT_PATHS
    try:
        findings, keys = scan(paths, args.root)
    except FileNotFoundError as e:
        print(f"keystone-lint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        doc = {
            "version": 1,
            "comment": "Accepted pre-existing keystone-lint findings. "
                       "Every entry needs a `why`; the gate fails on any "
                       "finding NOT in this file.",
            "entries": [
                {"key": k, "rule": f.rule, "why": "TODO: justify"}
                for f, k in zip(findings, keys)
            ],
        }
        bl_path = os.path.join(args.root, args.baseline)
        with open(bl_path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=False)
            f.write("\n")
        print(f"wrote {len(findings)} baseline entries to {args.baseline}")
        return 0

    baseline = None
    if not args.no_baseline:
        bl_path = os.path.join(args.root, args.baseline)
        if os.path.exists(bl_path):
            baseline = load_baseline(bl_path)
    fresh = new_findings(findings, keys, baseline)

    shown = findings if args.no_baseline else fresh
    if args.as_json:
        print(json.dumps({
            "findings": [f.as_dict() for f in shown],
            "total": len(findings),
            "baselined": len(findings) - len(fresh),
            "new": len(fresh),
        }))
    else:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from lint_report import format_findings  # shared formatter

        print(format_findings(
            [f.as_dict() for f in shown],
            title="keystone-lint (AST layer)",
        ))
        print(f"{len(findings)} finding(s), "
              f"{len(findings) - len(fresh)} baselined, {len(fresh)} new")
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
