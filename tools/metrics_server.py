"""Pull-based metrics/health export: /metrics + /healthz over HTTP.

The PR-4 registry made every process metric readable — but only
in-process. This is the scrape surface: a stdlib-only (http.server)
threaded HTTP server exposing

- ``/metrics`` — ``MetricsRegistry.prometheus()`` text exposition
  (counters, gauges, histogram buckets + percentiles, with the PR-5
  per-instance namespacing as an ``instance`` label), scrapeable by any
  Prometheus-compatible collector;
- ``/healthz`` — JSON health backed by ``PipelineService.stats()``:
  HTTP 200 while the dispatcher is alive and the service is open, 503
  once the worker died or the service closed — the load-balancer /
  kubelet probe shape;
- ``/solves`` — the streaming-solve health surface
  (``utils.flight_recorder.solver_stats``): per-solve units/rows done,
  rows/s, ETA, checkpoint age, and stall counts for every in-flight
  ``solve_least_squares_chunked`` / ``block_coordinate_descent_streamed``
  journey, so an hour-scale fit is pollable mid-flight.

Port comes from ``KEYSTONE_METRICS_PORT`` (``config.metrics_port``);
0 binds an ephemeral port (the smoke default — the chosen port is
reported). The server binds 127.0.0.1: this is an export surface for a
local scraper sidecar, not an authenticated public endpoint.

Usage:
    python tools/metrics_server.py            # smoke: serve, scrape,
                                              # validate, report, exit
    python tools/metrics_server.py --serve    # serve a demo service until
                                              # interrupted
    python tools/metrics_server.py --serve --port 9090

The smoke mode is ``make obs-serve`` and runs in-process under tier-1
(tests/test_flight_recorder.py): it stands up a real warmed service,
submits traffic, fetches both endpoints over an actual socket, validates
the Prometheus text against the shared ``validate_prometheus_text``
oracle, and cross-checks the scraped counts against
``metrics_registry.snapshot()`` — then closes the service and asserts
/healthz flips to 503.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class _Handler(BaseHTTPRequestHandler):
    """Routes /metrics and /healthz to the owning MetricsServer."""

    def do_GET(self):  # noqa: N802 (http.server API)
        owner: "MetricsServer" = self.server.owner  # type: ignore[attr-defined]
        if self.path.split("?")[0] == "/metrics":
            body = owner.render_metrics().encode()
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path.split("?")[0] == "/healthz":
            healthy, doc = owner.health()
            body = json.dumps(doc).encode()
            self.send_response(200 if healthy else 503)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path.split("?")[0] == "/solves":
            body = json.dumps(owner.solves()).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self.send_response(404)
        self.end_headers()

    def log_message(self, fmt, *args):  # quiet: scrapes are periodic
        pass


class MetricsServer:
    """The /metrics + /healthz HTTP endpoint over the process registry.

    ``health_source`` is a zero-arg callable returning a stats dict
    (canonically ``PipelineService.stats``); health is derived from its
    ``worker_alive``/``closed`` keys. Without a source, /healthz reports
    healthy process liveness only."""

    def __init__(
        self,
        port: Optional[int] = None,
        health_source: Optional[Callable[[], dict]] = None,
        registry=None,
    ):
        from keystone_tpu.config import config
        from keystone_tpu.utils.metrics import metrics_registry

        self.requested_port = (
            config.metrics_port if port is None else int(port)
        )
        self.health_source = health_source
        self.registry = registry if registry is not None else metrics_registry
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    def render_metrics(self) -> str:
        return self.registry.prometheus()

    def solves(self) -> dict:
        """The streaming-solve health surface for /solves: every
        in-flight solve's progress (units, rows/s, ETA, checkpoint age,
        stalls) plus the solver flight recorder's ring/dump summary."""
        from keystone_tpu.utils.flight_recorder import solver_stats

        return solver_stats()

    def health(self):
        """(healthy, body) for /healthz. Never raises: a health endpoint
        that 500s on a half-closed service defeats its purpose.

        Health sources that carry generation identity (the serving
        daemon's ``health_stats``) surface ``generation`` /
        ``artifact_fingerprint`` / ``draining`` at the top level, and a
        swap mid-drain reports 503 with ``draining: true`` so load
        balancers stop sending traffic before the flip."""
        if self.health_source is None:
            return True, {"healthy": True}
        try:
            stats = self.health_source()
        except Exception as e:  # lint: broad-ok probe must report, not raise
            return False, {"healthy": False, "error": str(e)[:200]}
        # THE health rule, shared with the daemon's own /healthz — the
        # two surfaces must never disagree about the same service. (No
        # new import weight: this process already imported
        # keystone_tpu.utils.metrics — and with it jax — to construct
        # the server.)
        from keystone_tpu.utils.flight_recorder import derive_health

        return derive_health(stats)

    def start(self) -> "MetricsServer":
        """Bind (ephemeral port when requested_port=0) and serve on a
        daemon thread; ``self.port`` is the actual bound port."""
        self._httpd = ThreadingHTTPServer(
            ("127.0.0.1", self.requested_port), _Handler
        )
        self._httpd.owner = self  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="keystone-metrics-server", daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def url(self, path: str) -> str:
        return f"http://127.0.0.1:{self.port}{path}"

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def _fetch(url: str):
    """GET url; returns (status, body string). stdlib only."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def run_smoke(port: Optional[int] = None, requests: int = 24) -> dict:
    """The ``make obs-serve`` flow: a live warmed service + metrics
    server, both endpoints fetched over a real socket and validated.
    Returns the verdict dict (``ok`` plus every gate)."""
    import numpy as np

    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures
    from keystone_tpu.utils.metrics import (
        metrics_registry,
        parse_prometheus_text,
        validate_prometheus_text,
    )
    from keystone_tpu.workflow.pipeline import FusedTransformer
    from keystone_tpu.workflow.serving import CompiledPipeline, PipelineService

    d = 16
    chain = FusedTransformer(
        [CosineRandomFeatures.create(d, 64, seed=0), L2Normalizer()]
    )
    cp = CompiledPipeline(chain, max_batch=16, devices=1).warmup((d,))
    rng = np.random.default_rng(0)
    svc = PipelineService(cp, max_delay_ms=1.0)
    server = MetricsServer(port=port, health_source=svc.stats).start()
    try:
        futs = [
            svc.submit(rng.normal(size=(d,)).astype(np.float32))
            for _ in range(requests)
        ]
        for f in futs:
            f.result(timeout=60)
        # Outcome counters are bumped AFTER the future resolves (the
        # completer's tail); settle before scraping so the agreement
        # gate compares two reads of the same final state instead of
        # racing the last bump.
        import time

        deadline = time.monotonic() + 10
        counters = metrics_registry.counters(f"serve.requests[{svc.name}]")
        while (
            counters.get("ok") < requests and time.monotonic() < deadline
        ):
            time.sleep(0.005)

        m_status, m_body = _fetch(server.url("/metrics"))
        prom_errors = validate_prometheus_text(m_body)
        # Scrape-vs-snapshot agreement: the ok-outcome count for THIS
        # service, read both ways.
        snap = metrics_registry.snapshot()
        ok_snap = snap[f"serve.requests[{svc.name}]"].get("ok", 0)
        ok_scraped = sum(
            s["value"] for s in parse_prometheus_text(m_body)
            if s["name"] == "keystone_serve_requests_total"
            and s["labels"].get("instance") == svc.name
            and s["labels"].get("key") == "ok"
        )
        h_status, h_body = _fetch(server.url("/healthz"))
        health = json.loads(h_body)
        s_status, s_body = _fetch(server.url("/solves"))
        solves = json.loads(s_body)
        svc.close()
        h2_status, h2_body = _fetch(server.url("/healthz"))
        health_closed = json.loads(h2_body)
        result = {
            "metric": "obs_serve_smoke",
            "port": server.port,
            "requests": requests,
            "metrics_status": m_status,
            "metrics_bytes": len(m_body),
            "prometheus_errors": prom_errors[:10],
            "ok_count_scraped": ok_scraped,
            "ok_count_snapshot": ok_snap,
            "healthz_status": h_status,
            "healthz_closed_status": h2_status,
            "solves_status": s_status,
            "pass": {
                "metrics_200": m_status == 200,
                "prometheus_valid": not prom_errors,
                "scrape_agrees_with_snapshot": ok_scraped == ok_snap
                and ok_snap >= requests,
                "healthz_200_while_open": h_status == 200
                and health.get("healthy") is True,
                "healthz_503_after_close": h2_status == 503
                and health_closed.get("healthy") is False,
                "solves_200_json": s_status == 200
                and "active_solves" in solves,
            },
        }
        result["ok"] = all(result["pass"].values())
        return result
    finally:
        server.stop()
        svc.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=None,
                    help="bind port (default KEYSTONE_METRICS_PORT; "
                         "0 = ephemeral)")
    ap.add_argument("--serve", action="store_true",
                    help="serve a demo service until interrupted instead "
                         "of running the smoke check")
    ap.add_argument("--requests", type=int, default=24,
                    help="smoke-mode request count")
    args = ap.parse_args(argv)

    if not args.serve:
        result = run_smoke(port=args.port, requests=args.requests)
        print(json.dumps(result))
        if result["ok"]:
            print("obs-serve smoke: PASS", file=sys.stderr)
        return 0 if result["ok"] else 1

    import numpy as np

    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.workflow.serving import CompiledPipeline, PipelineService

    cp = CompiledPipeline(L2Normalizer(), max_batch=16, devices=1)
    cp.warmup((8,))
    svc = PipelineService(cp, max_delay_ms=1.0)
    svc.submit(np.ones(8, np.float32)).result(timeout=30)
    with MetricsServer(port=args.port, health_source=svc.stats) as server:
        print(f"serving {server.url('/metrics')} and "
              f"{server.url('/healthz')} — Ctrl-C to stop", file=sys.stderr)
        try:
            threading.Event().wait()
        except KeyboardInterrupt:
            pass
        finally:
            svc.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
