"""Render keystone-lint findings — THE formatter both layers share.

CI and humans read one table shape whether the findings come from the
graph linter (``Pipeline.lint()`` / workflow/analysis.py) or the AST
invariant checker (tools/keystone_lint.py): severity, rule id, location
(node path or file:line), message, fix hint — the trace_report.py
aggregate-table idiom applied to diagnostics.

As a CLI this runs the GRAPH layer against the canonical serving
pipelines (the same fused chains tools/bench_serve.py and the serving
tests exercise) plus a deliberately-unserveable control chain, prints
the findings table, and exits 1 when any error-severity finding shows
up where none is expected — the demo half of ``make lint``
(tools/keystone_lint.py is the codebase half).

Usage:
    python tools/lint_report.py [--json]
    python tools/lint_report.py --findings FILE.json   # render any dump

Exit status: 0 = canonical pipelines lint clean (and the control chain
is correctly refused), 1 = unexpected findings / missed refusal.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_SEV_ORDER = {"error": 0, "warning": 1, "info": 2}


def format_findings(findings: List[dict], title: Optional[str] = None) -> str:
    """One table for both layers. Each finding dict carries rule /
    severity / message, plus either node (graph layer) or path+line
    (AST layer); hint optional."""
    lines: List[str] = []
    if title:
        lines.append(title)
    if not findings:
        lines.append("  clean — no findings")
        return "\n".join(lines)
    rows = []
    for f in sorted(
        findings,
        key=lambda f: (_SEV_ORDER.get(f.get("severity", "info"), 3),
                       f.get("rule", ""), f.get("path", ""),
                       f.get("line", 0)),
    ):
        where = f.get("node")
        if not where or where == "-":
            where = f"{f.get('path', '?')}:{f.get('line', '?')}"
        rows.append((f.get("severity", "?"), f.get("rule", "?"), where,
                     f.get("message", ""), f.get("hint", "")))
    w_sev = max(len(r[0]) for r in rows)
    w_rule = max(len(r[1]) for r in rows)
    w_where = min(44, max(len(r[2]) for r in rows))
    for sev, rule, where, msg, hint in rows:
        lines.append(f"  {sev:<{w_sev}}  {rule:<{w_rule}}  "
                     f"{where:<{w_where}}  {msg}")
        if hint:
            lines.append(f"  {'':<{w_sev}}  {'':<{w_rule}}  "
                         f"{'':<{w_where}}  -> {hint}")
    return "\n".join(lines)


def run_graph_demo() -> dict:
    """Lint the canonical serving chains (must be clean) and a row-coupled
    control chain (must be refused). Returns the machine-readable verdict
    ``make lint`` gates on."""
    import numpy as np

    from keystone_tpu.nodes.images.patches import RandomPatcher
    from keystone_tpu.nodes.learning.linear_mapper import LinearMapper
    from keystone_tpu.nodes.stats.hellinger import SignedHellingerMapper
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures
    from keystone_tpu.nodes.stats.scalers import StandardScalerModel
    from keystone_tpu.workflow import Pipeline

    rng = np.random.default_rng(0)
    d, D, k = 8, 16, 3
    fused_head = (
        StandardScalerModel(
            rng.normal(size=d).astype(np.float32),
            (1.0 + rng.uniform(size=d)).astype(np.float32),
        ).to_pipeline()
        .and_then(CosineRandomFeatures.create(d, D, seed=0))
        .and_then(SignedHellingerMapper())
        .and_then(L2Normalizer())
        .and_then(LinearMapper(rng.normal(size=(D, k)).astype(np.float32)))
    )
    canonical = {
        "fused-serving-head": fused_head,
        "normalize-map": L2Normalizer().and_then(
            LinearMapper(rng.normal(size=(d, k)).astype(np.float32))
        ),
    }
    control = RandomPatcher(4, 3).and_then(L2Normalizer())

    all_findings: List[dict] = []
    clean = True
    for name, p in canonical.items():
        report = p.lint(example=(d,), serve=True, have_ladder=True)
        for diag in report:
            f = diag.as_dict()
            f["pipeline"] = name
            all_findings.append(f)
        if report.errors() or report.warnings():
            clean = False
    control_report = control.lint(serve=True, have_ladder=True)
    control_rules = sorted({d.rule for d in control_report.errors()})
    refused = "KG002" in control_rules  # the row-coupled serveability rule
    return {
        "canonical_clean": clean,
        "control_refused": refused,
        "control_rules": control_rules,
        "findings": all_findings,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Render lint findings / run the graph-lint demo"
    )
    ap.add_argument("--findings", default=None,
                    help="JSON file of findings to render (skips the demo)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    if args.findings:
        with open(args.findings) as f:
            doc = json.load(f)
        findings = doc.get("findings", doc) if isinstance(doc, dict) else doc
        print(format_findings(findings, title="lint findings"))
        return 1 if any(
            f.get("severity") == "error" for f in findings
        ) else 0

    verdict = run_graph_demo()
    if args.as_json:
        print(json.dumps(verdict))
    else:
        print(format_findings(verdict["findings"],
                              title="graph lint (canonical pipelines)"))
        print(f"canonical_clean={verdict['canonical_clean']} "
              f"control_refused={verdict['control_refused']} "
              f"(control flagged: {', '.join(verdict['control_rules'])})")
    ok = verdict["canonical_clean"] and verdict["control_refused"]
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
