#!/usr/bin/env python3
"""bench-online: the drifting-data online-learning gate (`make bench-online`).

An Amazon-reviews-style label-shift drift, synthetically reproduced: a
model trains on phase-A data (class c clusters around mean M_c), then the
live stream silently permutes the label structure (the same feature
clusters now mean different classes — the sentiment-drift scenario).
A stale model's accuracy on the shifted stream collapses; the online
subsystem (``workflow/online.py``) folds the shifted batches into the
retained gram/AᵀB accumulators with time-decay, re-solves cheaply, and
hot-swaps the refreshed weights into a LIVE serving daemon mid-traffic.

Gates (the ISSUE-15 acceptance row):

- **recovery** (hard): post-refresh accuracy on the shifted stream —
  measured THROUGH THE DAEMON WIRE, generation > 0 — recovers to within
  ``RECOVERY_TOL`` of a full batch refit over the same shifted data.
- **refresh ≪ refit** (hard unless ``--quick``): the online re-solve
  wall (fold-state Cholesky, ``OnlineTrainer.resolve``) is at least
  ``MIN_RESOLVE_RATIO``× below the full-refit wall (re-featurize +
  full gram + solve). The asymmetry grows with history length — that is
  the point of retaining sufficient statistics.
- **zero dropped requests** (hard): open-loop traffic runs across the
  mid-stream hot-swap; every request answers 200 (the retrying client
  absorbs injected conn_drops exactly as under ``make chaos``), the
  daemon settles with zero active requests and zero unresolved
  journeys, and the generation visibly advances.

APPENDS the fingerprinted ``fit_online`` row to the BENCH_fit.json
history `make bench-watch` regresses against (recovery/accuracy leaves
higher-better, wall leaves lower-better, dropped/unresolved
lower-better).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: Post-refresh accuracy must land within this of the full-refit oracle.
RECOVERY_TOL = 0.05
#: The full refit must cost at least this many online re-solves.
MIN_RESOLVE_RATIO = 2.0


def make_drift_data(rng, n, d_in, k, scale=2.0, perm=None):
    """Clustered features with ±1 one-hot labels; ``perm`` relabels the
    clusters (label shift: same geometry, different meaning)."""
    means = scale * rng.normal(size=(k, d_in)).astype(np.float32)
    classes = rng.integers(0, k, size=n)
    X = (means[classes] + rng.normal(size=(n, d_in))).astype(np.float32)
    labels = classes if perm is None else perm[classes]
    Y = (np.eye(k, dtype=np.float32)[labels] * 2.0 - 1.0)
    return X, Y, labels


def accuracy(scores, labels) -> float:
    return float((np.asarray(scores).argmax(axis=1) == labels).mean())


def run_bench(args) -> dict:
    import jax

    from keystone_tpu.nodes.learning.linear_mapper import LinearMapEstimator
    from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures
    from keystone_tpu.utils.metrics import environment_fingerprint
    from keystone_tpu.workflow.daemon import ServingDaemon
    from keystone_tpu.workflow.online import OnlineTrainer
    from keystone_tpu.workflow.serialization import save_artifact

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        from serve_daemon import http_post
    finally:
        sys.path.pop(0)

    rng = np.random.default_rng(args.seed)
    d_in, k = args.dim, args.classes
    perm = np.roll(np.arange(k), 1)  # fixed-point-free label shift

    # One geometry for both phases: regenerate the SAME means by
    # re-seeding, permuting labels for phase B.
    rng_a = np.random.default_rng(args.seed)
    Xa, Ya, _ = make_drift_data(rng_a, args.rows, d_in, k)
    rng_b = np.random.default_rng(args.seed)
    Xb, Yb, _ = make_drift_data(
        rng_b, args.stream_batches * args.batch_rows, d_in, k, perm=perm
    )
    rng_t = np.random.default_rng(args.seed)
    # Fresh draws from the shifted regime for the held-out test set.
    n_test = args.rows + args.stream_batches * args.batch_rows
    Xt_all, _, lt_all = make_drift_data(rng_t, n_test, d_in, k, perm=perm)
    Xt, lt = Xt_all[args.rows:args.rows + args.test_rows], \
        lt_all[args.rows:args.rows + args.test_rows]

    # gamma sized to the cluster geometry (projection std ~1 radian):
    # the kernel keeps the class structure the drift demo pivots on.
    feat = CosineRandomFeatures.create(
        d_in, args.features, gamma=0.1, seed=args.seed
    )
    pipeline = feat.and_then(LinearMapEstimator(lam=args.lam), Xa, Ya)

    import tempfile

    workdir = tempfile.mkdtemp(prefix="bench_online_")
    fitted0 = pipeline.fit()
    art0 = os.path.join(workdir, "model-g0000.kart")
    save_artifact(fitted0, art0, feature_shape=(d_in,), dtype="float32")
    pre_acc = accuracy(np.asarray(fitted0.apply(Xt).get()), lt)

    bucket = args.batch_rows
    daemon = ServingDaemon(
        artifact=art0, http_port=0, enable_socket=False,
        buckets=(bucket,), max_batch=bucket,
    )
    trainer = OnlineTrainer(
        pipeline, daemon=daemon, artifact_dir=workdir,
        decay=args.decay, refresh_ms=0, start=False,
        feature_shape=(d_in,),
    )

    # Open-loop traffic across the whole stream + swap window.
    stop = threading.Event()
    served: list = []
    errors: list = []
    probe = Xt[:bucket].tolist()

    def traffic():
        while not stop.is_set():
            try:
                status, doc = http_post(
                    daemon.http_port, "/predict", {"x": probe}, timeout=30,
                    retries=8,
                )
                served.append((status, doc.get("generation")))
                if status != 200:
                    errors.append(doc)
            except Exception as e:  # lint: broad-ok an exhausted-retry client error must FAIL the zero-dropped gate, not kill the thread silently
                errors.append({"error": type(e).__name__, "message": str(e)})
            stop.wait(0.002)

    t = threading.Thread(target=traffic, daemon=True)
    t.start()
    try:
        for i in range(args.stream_batches):
            s = i * args.batch_rows
            trainer.submit(Xb[s:s + args.batch_rows],
                           Yb[s:s + args.batch_rows])
        # The re-solve wall: retained-state Cholesky only, no publish.
        resolve_walls = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            refreshed = trainer.resolve()
            jax.block_until_ready(
                refreshed.transformers()[-1].__dict__.get("W")
            )
            resolve_walls.append(time.perf_counter() - t0)
        resolve_wall = statistics.median(resolve_walls)
        # The full publish: re-solve + versioned artifact + hot-swap
        # under live traffic.
        t0 = time.perf_counter()
        trainer.refresh()
        refresh_wall = time.perf_counter() - t0
        # Post-refresh accuracy measured through the WIRE on the new
        # generation.
        correct = total = 0
        gen_seen = None
        for s in range(0, len(Xt), bucket):
            chunk, lchunk = Xt[s:s + bucket], lt[s:s + bucket]
            if len(chunk) < bucket:
                break
            status, doc = http_post(
                daemon.http_port, "/predict", {"x": chunk.tolist()},
                timeout=30, retries=8,
            )
            if status != 200:
                errors.append(doc)
                continue
            gen_seen = doc["generation"]
            pred = np.asarray(doc["y"], dtype=np.float32).argmax(axis=1)
            correct += int((pred == lchunk).sum())
            total += len(lchunk)
        post_acc = correct / max(total, 1)
    finally:
        stop.set()
        t.join(timeout=30)

    # Settle: every journey closed, nothing in flight.
    deadline = time.monotonic() + 30
    unresolved = None
    while time.monotonic() < deadline:
        snap = daemon._flight.snapshot()
        open_recs = [r for r in snap["records"] if r["outcome"] is None]
        if daemon.stats()["active_requests"] == 0 and not open_recs:
            unresolved = 0
            break
        time.sleep(0.02)
    if unresolved is None:
        snap = daemon._flight.snapshot()
        unresolved = len(
            [r for r in snap["records"] if r["outcome"] is None]
        ) + daemon.stats()["active_requests"]
    generation = daemon.generation
    daemon.close()
    trainer.close()

    # The full-refit oracle: a fresh batch fit over the same shifted
    # stream (new array identity — a cold fit, no cache assist).
    full_pipe = feat.and_then(
        LinearMapEstimator(lam=args.lam), np.array(Xb), np.array(Yb)
    )
    t0 = time.perf_counter()
    full_fitted = full_pipe.fit()
    jax.block_until_ready(full_fitted.transformers()[-1].__dict__.get("W"))
    full_refit_wall = time.perf_counter() - t0
    full_acc = accuracy(np.asarray(full_fitted.apply(Xt).get()), lt)

    gens = sorted({g for _s, g in served if g is not None})
    recovery_gate = post_acc >= full_acc - RECOVERY_TOL
    ratio = full_refit_wall / resolve_wall if resolve_wall > 0 else float(
        "inf")
    refresh_gate = ratio >= MIN_RESOLVE_RATIO
    swap_gate = (
        not errors and unresolved == 0 and generation >= 1
        and gen_seen is not None and gen_seen >= 1
    )
    drift_observed = post_acc > pre_acc + 0.1

    cores = os.cpu_count() or 1
    row = {
        "metric": "fit_online",
        "value": round(ratio, 1),
        "unit": "x re-solve speedup (full refit wall / online re-solve "
                "wall)",
        "backend": jax.default_backend(),
        "host_cores": cores,
        "env": environment_fingerprint(),
        "detail": {
            "rows_initial": args.rows,
            "stream_batches": args.stream_batches,
            "batch_rows": args.batch_rows,
            "dim": d_in,
            "features": args.features,
            "classes": k,
            "decay": args.decay,
            "reps": args.reps,
            "pre_refresh_accuracy": round(pre_acc, 4),
            "post_refresh_accuracy": round(post_acc, 4),
            "full_refit_accuracy": round(full_acc, 4),
            "accuracy_recovery": round(post_acc - pre_acc, 4),
            "resolve_wall_s": round(resolve_wall, 5),
            "refresh_wall_s": round(refresh_wall, 4),
            "full_refit_wall_s": round(full_refit_wall, 4),
            "requests_served": len(served),
            "dropped_requests": len(errors),
            "unresolved": unresolved,
            "generations_served": gens,
            "final_generation": generation,
            "drift_observed": drift_observed,
            "recovery_gate": recovery_gate,
            "refresh_gate": refresh_gate,
            "refresh_gate_is_hard": not getattr(args, "quick", False),
            "swap_gate": swap_gate,
        },
    }
    row["ok"] = bool(
        recovery_gate and swap_gate and drift_observed
        and (refresh_gate or getattr(args, "quick", False))
    )
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="online-learning drift/refresh bench: label-shifted "
                    "stream folded into retained accumulators, re-solved, "
                    "hot-swapped into a live daemon"
    )
    ap.add_argument("--rows", type=int, default=4096,
                    help="phase-A (pre-drift) training rows")
    ap.add_argument("--stream-batches", type=int, default=8)
    ap.add_argument("--batch-rows", type=int, default=256)
    ap.add_argument("--test-rows", type=int, default=1024)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--features", type=int, default=256,
                    help="random-feature width (the frozen featurize)")
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--decay", type=float, default=0.5,
                    help="per-fold time decay γ (drift tracking)")
    ap.add_argument("--lam", type=float, default=1e-3)
    ap.add_argument("--reps", type=int, default=3,
                    help="re-solve timings; the median is reported")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--quick", action="store_true",
                    help="tiny shapes, soft refresh-wall gate — harness "
                         "validation only, no row is written")
    ap.add_argument("--out", default=None,
                    help="append the fingerprinted JSONL row here")
    args = ap.parse_args(argv)

    if args.quick:
        args.rows, args.stream_batches, args.batch_rows = 512, 4, 64
        args.test_rows, args.features, args.reps = 256, 64, 1

    row = run_bench(args)
    print(json.dumps(row), flush=True)

    if args.out and not args.quick:
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")

    d = row["detail"]
    if not d["swap_gate"]:
        print(
            f"GATE FAILED: swap-under-refresh dropped requests "
            f"(dropped={d['dropped_requests']}, "
            f"unresolved={d['unresolved']}, "
            f"generation={d['final_generation']})", file=sys.stderr,
        )
        return 1
    if not d["recovery_gate"]:
        print(
            f"GATE FAILED: post-refresh accuracy "
            f"{d['post_refresh_accuracy']} did not recover to within "
            f"{RECOVERY_TOL} of the full refit "
            f"({d['full_refit_accuracy']})", file=sys.stderr,
        )
        return 1
    if not d["drift_observed"]:
        print("GATE FAILED: the drift demo did not degrade the stale "
              "model (no drift to recover from)", file=sys.stderr)
        return 1
    if not d["refresh_gate"] and not args.quick:
        print(
            f"GATE FAILED: online re-solve ({d['resolve_wall_s']}s) is "
            f"not ≥{MIN_RESOLVE_RATIO}x below the full refit "
            f"({d['full_refit_wall_s']}s)", file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    from keystone_tpu.utils.platform import setup_platform

    setup_platform()
    sys.exit(main())
