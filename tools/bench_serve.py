"""Shape-stable serving benchmark: per-shape jit vs bucketed + AOT-warmed.

Serves a mixed-size request trace (row counts drawn uniformly from
[1, max_batch]) through a representative fused serving head
(standardize -> cosine random features -> signed-Hellinger -> L2
normalize -> linear scores) two ways:

1. naive — today's ``Transformer.batch_call`` per-shape ``jax.jit``:
   every distinct row count recompiles the whole fused chain;
2. bucketed — ``workflow.serving.CompiledPipeline``: the pow-2 bucket
   ladder is AOT-compiled BEFORE traffic (``warmup``), every request is
   padded onto a bucket and served by a pre-compiled executable.

Reports steady-state p50/p99/mean request latency, throughput, and
compile counts for both paths (compiles are counted two ways: the
serving layer's own counter and a jax monitoring listener on XLA
compile-cache requests). The acceptance gate: ZERO compiles after
warmup on the bucketed path, and bucketed p99 at least 2x better than
naive. A third phase drives the ``PipelineService`` micro-batcher with
concurrent single-row clients and reports the coalescing ratio.

Usage: python tools/bench_serve.py [--requests 160] [--max-batch 256]
           [--out BENCH_serve.json]
Prints one JSON line and (with --out) writes the machine-readable
result for future PRs to regress against.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class CompileEventCounter:
    """Counts XLA compiles via jax.monitoring (each backend compile emits
    one '/jax/compilation_cache/compile_requests_use_cache' event).
    Listener registration is global and permanent, so one instance is
    created per process and phases snapshot its count."""

    EVENT = "/jax/compilation_cache/compile_requests_use_cache"

    def __init__(self):
        import jax

        self.count = 0
        jax.monitoring.register_event_listener(self._on_event)

    def _on_event(self, name, **kwargs):
        if name == self.EVENT:
            self.count += 1


def build_chain(d: int, features: int, classes: int, seed: int):
    """A fresh serving-head instance (fresh jit caches) over shared
    deterministic weights."""
    from keystone_tpu.nodes.learning.linear_mapper import LinearMapper
    from keystone_tpu.nodes.stats.hellinger import SignedHellingerMapper
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures
    from keystone_tpu.nodes.stats.scalers import StandardScalerModel
    from keystone_tpu.workflow.pipeline import FusedTransformer

    rng = np.random.default_rng(seed)
    return FusedTransformer(
        [
            StandardScalerModel(
                rng.normal(size=d).astype(np.float32),
                (1.0 + rng.uniform(size=d)).astype(np.float32),
            ),
            CosineRandomFeatures.create(d, features, seed=seed),
            SignedHellingerMapper(),
            L2Normalizer(),
            LinearMapper(
                (rng.normal(size=(features, classes)) / np.sqrt(features))
                .astype(np.float32)
            ),
        ]
    )


def lat_stats(lats_s) -> dict:
    ms = np.asarray(lats_s) * 1e3
    return {
        "p50_ms": round(float(np.percentile(ms, 50)), 3),
        "p99_ms": round(float(np.percentile(ms, 99)), 3),
        "mean_ms": round(float(ms.mean()), 3),
        "total_s": round(float(ms.sum() / 1e3), 3),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=160,
                    help="requests in the mixed-size trace")
    ap.add_argument("--max-batch", type=int, default=256,
                    help="largest request row count / top serving bucket")
    ap.add_argument("--d", type=int, default=64, help="input feature dim")
    ap.add_argument("--features", type=int, default=512,
                    help="random-feature width of the serving head")
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--service-clients", type=int, default=4,
                    help="concurrent single-row clients for the "
                    "micro-batcher phase (0 skips it)")
    ap.add_argument("--service-requests", type=int, default=200,
                    help="total single-row requests across clients")
    ap.add_argument("--out", type=str, default=None,
                    help="also write the JSON result to this path")
    args = ap.parse_args()

    from keystone_tpu.utils.platform import ensure_live_backend

    backend = ensure_live_backend()
    import jax

    from keystone_tpu.config import config
    from keystone_tpu.utils.metrics import serving_counters
    from keystone_tpu.workflow.serving import (
        CompiledPipeline,
        PipelineService,
        _jit_cache_size,
    )

    # The baseline phase must measure TRUE per-shape jit: an inherited
    # KEYSTONE_SERVE_BUCKETS would silently route batch_call through
    # bucketing and collapse the comparison to bucketed-vs-bucketed.
    config.serve_buckets = ()

    compile_events = CompileEventCounter()
    rng = np.random.default_rng(args.seed)
    sizes = rng.integers(1, args.max_batch + 1, size=args.requests)
    trace = [
        rng.normal(size=(int(n), args.d)).astype(np.float32) for n in sizes
    ]

    # -- naive: per-shape jit ------------------------------------------------
    naive = build_chain(args.d, args.features, args.classes, args.seed)
    # One warm call at the top size — the naive server has seen SOME traffic;
    # every new row count in the trace still recompiles.
    jax.block_until_ready(naive.batch_call(trace[0][: args.max_batch]))
    ev0 = compile_events.count
    naive_lats = []
    t0 = time.perf_counter()
    for x in trace:
        t1 = time.perf_counter()
        jax.block_until_ready(naive.batch_call(x))
        naive_lats.append(time.perf_counter() - t1)
    naive_wall = time.perf_counter() - t0
    naive_compiles = compile_events.count - ev0

    # -- bucketed + AOT warmup -----------------------------------------------
    serving_counters.reset()
    cp = CompiledPipeline(
        build_chain(args.d, args.features, args.classes, args.seed),
        max_batch=args.max_batch,
    )
    ev0 = compile_events.count
    cp.warmup((args.d,))
    warmup_compiles = compile_events.count - ev0
    ev0 = compile_events.count
    bucketed_lats = []
    t0 = time.perf_counter()
    for x in trace:
        t1 = time.perf_counter()
        cp(x)  # host-out: the np result is already synchronized
        bucketed_lats.append(time.perf_counter() - t1)
    bucketed_wall = time.perf_counter() - t0
    post_warmup_compiles = compile_events.count - ev0

    rows = int(sizes.sum())
    naive_p99 = float(np.percentile(np.asarray(naive_lats) * 1e3, 99))
    bucketed_p99 = float(np.percentile(np.asarray(bucketed_lats) * 1e3, 99))

    result = {
        "metric": "serve_bucketed_vs_pershape",
        "backend": backend,
        "host_cores": os.cpu_count(),
        "requests": args.requests,
        "rows": rows,
        "d": args.d,
        "features": args.features,
        "classes": args.classes,
        "ladder": list(cp.ladder),
        "naive": {
            **lat_stats(naive_lats),
            "rows_per_s": round(rows / naive_wall, 1),
            "compiles": naive_compiles,
            "jit_cache_entries": _jit_cache_size(naive._jitted()),
        },
        "bucketed": {
            **lat_stats(bucketed_lats),
            "rows_per_s": round(rows / bucketed_wall, 1),
            "warmup_seconds": round(cp.warmup_seconds, 3),
            "warmup_compiles": warmup_compiles,
            "post_warmup_compiles": post_warmup_compiles,
            "serving_counter_compiles_post_warmup": (
                serving_counters.snapshot()["compiles"] - len(cp.ladder)
            ),
            "pad_overhead": round(
                serving_counters.snapshot()["pad_overhead"], 4
            ),
            "bucket_hits": serving_counters.snapshot()["bucket_hits"],
        },
        "speedup": {
            "p50": round(
                float(np.percentile(np.asarray(naive_lats) * 1e3, 50))
                / float(np.percentile(np.asarray(bucketed_lats) * 1e3, 50)),
                2,
            ),
            "p99": round(naive_p99 / bucketed_p99, 2),
            "throughput": round(naive_wall / bucketed_wall, 2),
        },
        "pass": {
            "zero_post_warmup_compiles": post_warmup_compiles == 0,
            "p99_speedup_ge_2x": naive_p99 / bucketed_p99 >= 2.0,
        },
    }

    # -- micro-batcher: concurrent single-row clients -------------------------
    if args.service_clients > 0:
        per_client = max(1, args.service_requests // args.service_clients)
        lats, lock = [], threading.Lock()

        def client(cid: int):
            crng = np.random.default_rng(1000 + cid)
            mine = []
            for _ in range(per_client):
                x = crng.normal(size=(args.d,)).astype(np.float32)
                t1 = time.perf_counter()
                svc.submit(x).result()
                mine.append(time.perf_counter() - t1)
            with lock:
                lats.extend(mine)

        with PipelineService(cp, max_delay_ms=2.0) as svc:
            threads = [
                threading.Thread(target=client, args=(c,))
                for c in range(args.service_clients)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            svc_wall = time.perf_counter() - t0
            stats = svc.stats()
        result["service"] = {
            **lat_stats(lats),
            "clients": args.service_clients,
            "requests": stats["requests"],
            "device_batches": stats["batches_run"],
            "coalesce_ratio": round(stats["coalesce_ratio"], 2),
            "rows_per_s": round(stats["rows_served"] / svc_wall, 1),
        }

    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
