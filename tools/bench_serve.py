"""Shape-stable serving benchmark: per-shape jit vs bucketed + AOT-warmed.

Serves a mixed-size request trace (row counts drawn uniformly from
[1, max_batch]) through a representative fused serving head
(standardize -> cosine random features -> signed-Hellinger -> L2
normalize -> linear scores) two ways:

1. naive — today's ``Transformer.batch_call`` per-shape ``jax.jit``:
   every distinct row count recompiles the whole fused chain;
2. bucketed — ``workflow.serving.CompiledPipeline``: the pow-2 bucket
   ladder is AOT-compiled BEFORE traffic (``warmup``), every request is
   padded onto a bucket and served by a pre-compiled executable.

Reports steady-state p50/p99/mean request latency, throughput, and
compile counts for both paths (compiles are counted two ways: the
serving layer's own counter and a jax monitoring listener on XLA
compile-cache requests). The acceptance gate: ZERO compiles after
warmup on the bucketed path, and bucketed p99 at least 2x better than
naive. A third phase drives the ``PipelineService`` micro-batcher with
concurrent single-row clients and reports the coalescing ratio.

Usage: python tools/bench_serve.py [--requests 160] [--max-batch 256]
           [--out BENCH_serve.json]
Prints one JSON line and (with --out) writes the machine-readable
result for future PRs to regress against.

``--overload`` runs the hardening bench instead: calibrate the
micro-batcher's closed-loop capacity, then drive it OPEN-loop at 2x
sustained over-capacity against a bounded pending queue and per-request
deadlines. Reports the fast-fail rate (QueueFullError + DeadlineExceeded
— rejections that cost no device time), accepted-request p99, and the
no-stranded-future invariant. The gate: excess load turns into fast
failures while accepted p99 stays bounded by the deadline — degradation,
not a cliff.

``--precision`` runs the memory-bounded precision A/B instead: the f32
HAND-PICKED ladder (one bucket at the provisioned maximum — the
pad-everything-to-max config) vs the HBM-PLANNED ladder served at bf16
through the same trained canonical head. Gates hard on any backend:
planned+bf16 beats the baseline on wall AND p99 (pad-overhead structure,
not core count), the default-built engine serves bit-identically to the
explicit-f32 engine on the same ladder (the knob-off contract), the
ladder change itself moves answers at most float noise, the multiclass
quality gate stays within its declared tolerance of the f32 oracle
(``CompiledPipeline.qualify`` refuses otherwise), zero post-warmup
compiles; the appended ``serve_precision`` row carries the planner's
per-bucket bytes + provenance under bench_watch.

``--devices N`` runs the replica-scaling bench instead: the same uniform
mixed-size trace is served at devices=1 and devices=N through the
pipelined micro-batcher (``make bench-serve-replicas`` forces the
8-host-device CPU mesh via --xla_force_host_platform_device_count=8).
Reports per-pool-width throughput, the dispatch-balance counters
(max/min ≤ 3x gate), and a bit-identity check of replica outputs against
the single-device engine; the row APPENDS to --out so the scaling
evidence accumulates next to the main serving anchor. The hard ≥1.3x
throughput gate only applies when the fingerprint shows ≥2 host cores —
on a 1-core container N replicas time-slice one core, so the gate there
is merely "no worse".
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_chain(d: int, features: int, classes: int, seed: int):
    """A fresh serving-head instance (fresh jit caches) over shared
    deterministic weights."""
    from keystone_tpu.nodes.learning.linear_mapper import LinearMapper
    from keystone_tpu.nodes.stats.hellinger import SignedHellingerMapper
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures
    from keystone_tpu.nodes.stats.scalers import StandardScalerModel
    from keystone_tpu.workflow.pipeline import FusedTransformer

    rng = np.random.default_rng(seed)
    return FusedTransformer(
        [
            StandardScalerModel(
                rng.normal(size=d).astype(np.float32),
                (1.0 + rng.uniform(size=d)).astype(np.float32),
            ),
            CosineRandomFeatures.create(d, features, seed=seed),
            SignedHellingerMapper(),
            L2Normalizer(),
            LinearMapper(
                (rng.normal(size=(features, classes)) / np.sqrt(features))
                .astype(np.float32)
            ),
        ]
    )


def write_result(path: str, line: str, metric: str) -> None:
    """One latest row per metric in the JSONL evidence file: rewrite
    keeping other metrics' rows, so the main anchor, the overload row,
    and the replica-scaling row coexist in --out without any mode's
    writer wiping another's evidence."""
    rows = []
    if os.path.exists(path):
        with open(path) as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    if json.loads(raw).get("metric") == metric:
                        continue  # superseded by this run
                except ValueError:
                    pass
                rows.append(raw)
    rows.append(line)
    # Atomic rewrite (the disk_cache.py idiom): an interrupt mid-write
    # must not destroy the OTHER modes' accumulated evidence rows.
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write("\n".join(rows) + "\n")
    os.replace(tmp, path)


def lat_stats(lats_s) -> dict:
    ms = np.asarray(lats_s) * 1e3
    return {
        "p50_ms": round(float(np.percentile(ms, 50)), 3),
        "p99_ms": round(float(np.percentile(ms, 99)), 3),
        "mean_ms": round(float(ms.mean()), 3),
        "total_s": round(float(ms.sum() / 1e3), 3),
    }


def nearest_rank_ms(lats_s, p: float) -> float:
    """Nearest-rank percentile in ms — the estimator the registry's
    log-bucket histogram implements, used for the agreement cross-check so
    both sides measure the SAME order statistic (numpy's default linear
    interpolation can smooth across a tail jump that nearest-rank, by
    design, reports)."""
    import math

    s = sorted(lats_s)
    return s[max(0, math.ceil(len(s) * p / 100.0) - 1)] * 1e3


def run_overload(cp, args) -> dict:
    """2x-capacity open-loop hammering of the bounded-queue service."""
    from keystone_tpu.utils.reliability import (
        DeadlineExceeded,
        QueueFullError,
        ServiceClosed,
    )
    from keystone_tpu.workflow.serving import PipelineService

    x = np.zeros((args.d,), dtype=np.float32)
    clients = max(1, args.service_clients)

    # -- calibration. The service's capacity is flushes/s x rows/flush.
    # An unbounded row budget makes a coalescing service effectively
    # saturation-proof from a handful of host threads (one flush absorbs
    # hundreds of rows), so the overload scenario pins max_rows — the
    # stand-in for a device already at its batch budget — and capacity
    # follows from the measured per-flush latency at that budget.
    xb = np.zeros((args.overload_max_rows, args.d), dtype=np.float32)
    for _ in range(5):
        cp(xb)
    n_cal = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < args.calibrate_seconds or n_cal < 10:
        cp(xb)
        n_cal += 1
    t_flush = (time.perf_counter() - t0) / n_cal
    capacity_rps = args.overload_max_rows / t_flush

    # -- open loop at 2x: clients submit on a fixed clock, never waiting
    # for results, so the offered rate really is 2x what the service can
    # sustain — the queue must absorb or reject the difference.
    offered_rps = 2.0 * capacity_rps
    interval = clients / offered_rps
    lock = threading.Lock()
    accepted_lat, outcomes = [], {
        "ok": 0, "rejected": 0, "expired": 0, "closed": 0, "error": 0,
    }
    futures = []

    svc = PipelineService(
        cp,
        max_delay_ms=0.5,
        max_rows=args.overload_max_rows,
        max_pending=args.overload_max_pending,
        deadline_ms=args.overload_deadline_ms,
    )

    def on_done(fut, t_submit):
        lat = time.perf_counter() - t_submit
        exc = fut.exception()
        with lock:
            if exc is None:
                outcomes["ok"] += 1
                accepted_lat.append(lat)
            elif isinstance(exc, DeadlineExceeded):
                outcomes["expired"] += 1
            elif isinstance(exc, ServiceClosed):
                outcomes["closed"] += 1
            else:
                outcomes["error"] += 1

    def open_loop(cid):
        end = time.perf_counter() + args.overload_seconds
        next_t = time.perf_counter() + (cid / clients) * interval
        while time.perf_counter() < end:
            pause = next_t - time.perf_counter()
            if pause > 0:
                time.sleep(pause)
            next_t += interval
            t1 = time.perf_counter()
            try:
                fut = svc.submit(x)
            except QueueFullError:
                with lock:
                    outcomes["rejected"] += 1
                continue
            with lock:
                futures.append(fut)
            fut.add_done_callback(lambda f, t1=t1: on_done(f, t1))

    threads = [
        threading.Thread(target=open_loop, args=(c,)) for c in range(clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    svc.close()  # drains; MUST leave no future unresolved
    unresolved = sum(not f.done() for f in futures)
    total = sum(outcomes.values())
    fast_fails = outcomes["rejected"] + outcomes["expired"]
    acc = lat_stats(accepted_lat) if accepted_lat else None
    # The deadline bounds time-in-queue; execution adds at most a batch.
    p99_bound_ms = 2.0 * args.overload_deadline_ms
    return {
        "clients": clients,
        "flush_ms": round(t_flush * 1e3, 3),
        "max_rows_per_flush": args.overload_max_rows,
        "capacity_rps": round(capacity_rps, 1),
        "offered_rps": round(offered_rps, 1),
        "offered_requests": total,
        "max_pending": args.overload_max_pending,
        "deadline_ms": args.overload_deadline_ms,
        "outcomes": outcomes,
        "fast_fail_rate": round(fast_fails / total, 4) if total else None,
        "accepted": acc,
        "unresolved_futures": unresolved,
        "service": svc.stats(),
        "pass": {
            "no_stranded_futures": unresolved == 0,
            "backpressure_engaged": fast_fails > 0,
            "accepted_p99_bounded": bool(
                acc and acc["p99_ms"] <= p99_bound_ms
            ),
        },
    }


def run_daemon_bench(args) -> dict:
    """Open-loop load at 2x measured capacity through the REAL socket
    ingress of the serving daemon, with two hot-swaps performed under
    the sustained flood.

    Tenants: one gold (protected: reserved budget headroom + deadline)
    probed closed-loop for its p99, one best-effort flood driven
    open-loop at 2x the capacity measured closed-loop through the same
    wire. Gates: backpressure engages (fast-fail 429/504 on the excess
    instead of a latency cliff), gold p99 stays within 2x its deadline,
    both swaps succeed with responses spanning >= 2 generations, and
    every request issued gets exactly one response (zero
    dropped/unresolved)."""
    import tempfile

    import serve_daemon as sd  # tools/ is on sys.path when run as a script

    from keystone_tpu.workflow.daemon import ServingDaemon, Tenant
    from keystone_tpu.workflow.serialization import save_artifact

    d = args.d
    out_dir = tempfile.mkdtemp(prefix="keystone_daemon_bench_")
    arts = []
    for seed in (args.seed, args.seed + 1):
        chain = build_chain(d, args.features, args.classes, seed)
        pipe = chain.to_pipeline().fit()
        path = os.path.join(out_dir, f"model_s{seed}.kart")
        save_artifact(pipe, path, feature_shape=(d,), dtype="float32")
        arts.append(path)

    # Admission capacity is the daemon's pending budget: best-effort is
    # refused past BE_BUDGET_FRAC of it. The flood offers 2x that
    # concurrency through the real socket, so the excess MUST fast-fail
    # at admission (429 before any device work) while gold rides its
    # reserved headroom.
    pending_budget = max(4, args.service_clients)
    from keystone_tpu.workflow.daemon import BE_BUDGET_FRAC

    be_limit = max(1, int(pending_budget * BE_BUDGET_FRAC))
    clients = 2 * be_limit
    tenants = {
        "bk-gold": Tenant("gold", "bk-gold", qps=0, tier="gold"),
        "bk-be": Tenant("flood", "bk-be", qps=0, tier="best_effort"),
    }
    daemon = ServingDaemon(
        artifact=arts[0], tenants=tenants, devices=1,
        max_batch=args.overload_max_rows * 2,
        max_rows=args.overload_max_rows,
        max_delay_ms=0.5,
        max_pending=args.overload_max_pending,
        pending_budget=pending_budget,
        gold_deadline_ms=args.overload_deadline_ms,
        be_deadline_ms=args.overload_deadline_ms,
        name="bench-daemon",
        swap_token="bench-swap-token",
    )
    x_row = np.zeros((d,), dtype=np.float32).tolist()
    lock = threading.Lock()

    try:
        # -- calibrate: sustained within-budget closed-loop capacity
        # through the wire (be_limit concurrent connections = exactly
        # the admitted best-effort concurrency).
        def closed_loop(stop_t, counter):
            sc = sd.SocketClient(daemon.socket_port)
            n = 0
            try:
                while time.perf_counter() < stop_t:
                    resp = sc.request({"x": x_row, "key": "bk-be"})
                    if resp.get("status") == 200:
                        n += 1
            finally:
                sc.close()
                with lock:
                    counter.append(n)

        cal_counts: list = []
        t_end = time.perf_counter() + args.calibrate_seconds
        cal_threads = [
            threading.Thread(target=closed_loop, args=(t_end, cal_counts))
            for _ in range(be_limit)
        ]
        t0 = time.perf_counter()
        for t in cal_threads:
            t.start()
        for t in cal_threads:
            t.join()
        cal_wall = time.perf_counter() - t0
        capacity_rps = sum(cal_counts) / cal_wall

        # -- flood: 2x the admitted concurrency hammering the socket;
        # gold probes closed-loop via HTTP; two hot-swaps land mid-flood.
        outcomes = {"ok": 0, "rejected": 0, "expired": 0, "closed": 0,
                    "error": 0, "conn": 0}
        gens_seen = set()
        gold_lats: list = []
        gold_errors: list = []
        swap_results: list = []
        stop = threading.Event()

        def flood(cid):
            sc = sd.SocketClient(daemon.socket_port)
            end = time.perf_counter() + args.overload_seconds
            try:
                while time.perf_counter() < end:
                    try:
                        resp = sc.request({"x": x_row, "key": "bk-be"})
                    except (ConnectionError, OSError):
                        with lock:
                            outcomes["conn"] += 1
                        sc.close()
                        sc = sd.SocketClient(daemon.socket_port)
                        continue
                    status = resp.get("status")
                    with lock:
                        if status == 200:
                            outcomes["ok"] += 1
                            gens_seen.add(resp.get("generation"))
                        elif status == 429:
                            outcomes["rejected"] += 1
                        elif status == 504:
                            outcomes["expired"] += 1
                        elif status == 503:
                            outcomes["closed"] += 1
                        else:
                            outcomes["error"] += 1
            finally:
                sc.close()

        def gold_probe():
            while not stop.is_set():
                t1 = time.perf_counter()
                st, doc = sd.http_post(
                    daemon.http_port, "/predict", {"x": x_row},
                    {"X-API-Key": "bk-gold"},
                )
                if st == 200:
                    gold_lats.append(time.perf_counter() - t1)
                    gens_seen.add(doc.get("generation"))
                else:
                    gold_errors.append((st, doc.get("error")))
                time.sleep(0.01)

        def swapper():
            # Two swaps spread across the flood window. retries=1: /swap
            # is not idempotent — a retried ack-lost swap would run twice.
            for i, path in enumerate((arts[1], arts[0])):
                time.sleep(args.overload_seconds / 3.0)
                st, doc = sd.http_post(
                    daemon.http_port, "/swap", {"artifact": path},
                    {"X-Swap-Token": "bench-swap-token"},
                    timeout=120, retries=1,
                )
                swap_results.append((st, doc))

        flood_threads = [
            threading.Thread(target=flood, args=(c,)) for c in range(clients)
        ]
        gold_t = threading.Thread(target=gold_probe, daemon=True)
        swap_t = threading.Thread(target=swapper)
        for t in flood_threads:
            t.start()
        gold_t.start()
        swap_t.start()
        for t in flood_threads:
            t.join()
        swap_t.join()
        stop.set()
        gold_t.join(timeout=30)

        stats = daemon.stats()
        total = sum(outcomes.values())
        fast_fails = outcomes["rejected"] + outcomes["expired"]
        gold = lat_stats(gold_lats) if gold_lats else None
        p99_bound_ms = 2.0 * args.overload_deadline_ms
        swaps_ok = (
            len(swap_results) == 2
            and all(st == 200 for st, _ in swap_results)
        )
        gold_total = len(gold_lats) + len(gold_errors)
        gold_ok_frac = len(gold_lats) / gold_total if gold_total else None
        offered_rps = total / max(args.overload_seconds, 1e-9)
        result = {
            "metric": "serve_daemon",
            "unit": "ms",
            "clients": clients,
            "pending_budget_admission": pending_budget,
            "be_admission_limit": be_limit,
            "capacity_rps": round(capacity_rps, 1),
            "offered_rps": round(offered_rps, 1),
            "offered_requests": total,
            "deadline_ms": args.overload_deadline_ms,
            "service_max_pending": args.overload_max_pending,
            "outcomes": outcomes,
            "fast_fail_rate": round(fast_fails / total, 4) if total else None,
            "gold": gold,
            "gold_ok_frac": (
                round(gold_ok_frac, 4) if gold_ok_frac is not None else None
            ),
            "gold_errors": gold_errors[:10],
            "generations_seen": sorted(
                g for g in gens_seen if g is not None
            ),
            "swaps": stats["swaps"],
            "active_leftover": stats["active_requests"],
            "pass": {
                "backpressure_engaged": fast_fails > 0,
                "gold_p99_bounded": bool(
                    gold and gold["p99_ms"] <= p99_bound_ms
                ),
                # A lone gold 504 riding a swap-compile stall on a 1-core
                # host is noise; sustained gold rejection is the failure.
                "gold_mostly_served": bool(
                    gold_ok_frac is not None and gold_ok_frac >= 0.95
                ),
                "swap_under_load_ok": swaps_ok,
                "two_generations_served": len(gens_seen) >= 2,
                "zero_unresolved": (
                    stats["active_requests"] == 0 and outcomes["conn"] == 0
                    and outcomes["error"] == 0
                ),
            },
        }
        result["ok"] = all(result["pass"].values())
        return result
    finally:
        daemon.close()


def run_telemetry_bench(args) -> dict:
    """Telemetry-on vs telemetry-off A/B flood through the daemon's
    socket ingress: the same closed-loop load served twice, once with
    durable journey export off (KEYSTONE_TELEMETRY_DIR unset) and once
    with it writing to a scratch directory.

    Gates: the telemetry-on phase stays within a bounded throughput
    overhead of the off phase (the writer thread + queue handoff is the
    ONLY added hot-path work, so a large gap means the export leaked
    into admission), every enqueued record is accounted for as either
    durably written or counted-dropped after the close-time drain (the
    drops-counted-never-blocks contract), and the on-phase journeys are
    actually recoverable from disk."""
    import glob as _glob
    import tempfile

    import serve_daemon as sd  # tools/ is on sys.path when run as a script

    from keystone_tpu.workflow.daemon import ServingDaemon
    from keystone_tpu.workflow.serialization import save_artifact
    from keystone_tpu.utils.telemetry import active_telemetry, reset_telemetry

    d = args.d
    out_dir = tempfile.mkdtemp(prefix="keystone_telemetry_bench_")
    chain = build_chain(d, args.features, args.classes, args.seed)
    pipe = chain.to_pipeline().fit()
    art = os.path.join(out_dir, "model.kart")
    save_artifact(pipe, art, feature_shape=(d,), dtype="float32")

    x_row = np.zeros((d,), dtype=np.float32).tolist()
    clients = max(2, args.service_clients)
    seconds = args.telemetry_seconds
    lock = threading.Lock()

    def run_phase(tag: str, telemetry_dir: str | None) -> dict:
        if telemetry_dir is None:
            os.environ.pop("KEYSTONE_TELEMETRY_DIR", None)
        else:
            os.environ["KEYSTONE_TELEMETRY_DIR"] = telemetry_dir
        reset_telemetry()
        daemon = ServingDaemon(
            artifact=art, devices=1, max_delay_ms=0.5,
            name=f"telemetry-bench-{tag}",
        )
        counts: list = []
        lats: list = []
        try:
            def closed_loop():
                sc = sd.SocketClient(daemon.socket_port)
                n = 0
                mine: list = []
                end = time.perf_counter() + seconds
                try:
                    while time.perf_counter() < end:
                        t1 = time.perf_counter()
                        resp = sc.request({"x": x_row})
                        if resp.get("status") == 200:
                            n += 1
                            mine.append(time.perf_counter() - t1)
                finally:
                    sc.close()
                    with lock:
                        counts.append(n)
                        lats.extend(mine)

            threads = [threading.Thread(target=closed_loop)
                       for _ in range(clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
        finally:
            daemon.close()  # drains the telemetry queue before return
        tel = active_telemetry()
        tstats = tel.stats() if tel is not None else None
        reset_telemetry()
        served = sum(counts)
        return {
            "served": served,
            "req_per_s": served / max(wall, 1e-9),
            "lat": lat_stats(lats) if lats else None,
            "telemetry": tstats,
        }

    prior_env = os.environ.get("KEYSTONE_TELEMETRY_DIR")
    try:
        off = run_phase("off", None)
        tel_dir = os.path.join(out_dir, "telemetry")
        on = run_phase("on", tel_dir)
    finally:
        if prior_env is None:
            os.environ.pop("KEYSTONE_TELEMETRY_DIR", None)
        else:
            os.environ["KEYSTONE_TELEMETRY_DIR"] = prior_env
        reset_telemetry()

    overhead = max(0.0, 1.0 - on["req_per_s"] / max(off["req_per_s"], 1e-9))
    ts = on["telemetry"] or {}
    enqueued = int(ts.get("enqueued", 0))
    written = int(ts.get("written", 0))
    dropped = int(ts.get("dropped", 0))
    journeys_on_disk = 0
    for seg in _glob.glob(os.path.join(tel_dir, "keystone_telemetry_*.jsonl")):
        with open(seg, "r", encoding="utf-8") as fh:
            for raw in fh:
                try:
                    rec = json.loads(raw)
                except ValueError:
                    continue
                if rec.get("kind") == "journey":
                    journeys_on_disk += 1

    result = {
        "metric": "serve_telemetry",
        "unit": "req/s",
        "clients": clients,
        "seconds": seconds,
        "off": {"req_per_s": round(off["req_per_s"], 1),
                "served": off["served"], "lat": off["lat"]},
        "on": {"req_per_s": round(on["req_per_s"], 1),
               "served": on["served"], "lat": on["lat"]},
        "overhead_frac": round(overhead, 4),
        "overhead_bound": args.telemetry_overhead_bound,
        "records_enqueued": enqueued,
        "records_written": written,
        "records_dropped": dropped,
        "journeys_on_disk": journeys_on_disk,
        "pass": {
            "overhead_bounded": overhead <= args.telemetry_overhead_bound,
            "telemetry_engaged": enqueued > 0 and written > 0,
            # The never-blocks contract: after the close-time drain every
            # enqueued record is durably written or counted as dropped —
            # nothing stalls in the queue, nothing vanishes uncounted.
            "nonblocking_accounted": enqueued == written + dropped,
            # Every journey that was not a counted drop is on disk.
            "journeys_recoverable": (
                journeys_on_disk >= on["served"] - dropped
            ),
        },
    }
    result["ok"] = all(result["pass"].values())
    return result


def build_trained_chain(d: int, features: int, classes: int, seed: int,
                        n_train: int = 2048, n_eval: int = 512):
    """The quality-gated serving head: the canonical featurize chain with
    its linear map TRAINED (least squares on margin-separated synthetic
    classes) instead of random — random weights leave argmax margins at
    quantization scale, which is not the scenario a precision ladder
    serves. Returns ``(chain, X_eval, y_eval)``."""
    from keystone_tpu.nodes.learning.linear_mapper import LinearMapper
    from keystone_tpu.workflow.pipeline import FusedTransformer

    base = build_chain(d, features, classes, seed)
    prefix = FusedTransformer(base.stages[:-1])
    rng = np.random.default_rng(seed + 1)
    centroids = rng.normal(size=(classes, d)).astype(np.float32) * 2.0
    y = rng.integers(0, classes, n_train)
    X = (centroids[y] + 0.3 * rng.normal(size=(n_train, d))).astype(
        np.float32
    )
    F = np.asarray(prefix.batch_call(X))
    Y = np.eye(classes, dtype=np.float32)[y]
    W, *_ = np.linalg.lstsq(F, Y, rcond=None)
    chain = FusedTransformer(
        base.stages[:-1] + [LinearMapper(W.astype(np.float32))]
    )
    ye = rng.integers(0, classes, n_eval)
    Xe = (centroids[ye] + 0.3 * rng.normal(size=(n_eval, d))).astype(
        np.float32
    )
    return chain, Xe, ye


def run_precision_bench(args) -> dict:
    """Memory-bounded serving A/B: the f32 HAND-PICKED ladder (one bucket
    at the provisioned maximum — the classic pad-everything-to-max AOT
    config, config.serve_buckets-style) vs the HBM-PLANNED ladder served
    at bf16 precision, on the same mixed-size trace through the same
    trained canonical head.

    Gates (hard on any backend — the win is pad-overhead structure, not
    core count): planned+bf16 beats the hand-picked f32 baseline on wall
    AND p99; the default-built engine is BIT-identical to the explicit
    f32 engine on the same ladder (the knob-off contract — the default
    path is today's construction, untouched) while the ladder change
    itself moves answers at most float noise (bit-identity across
    DIFFERENT bucket shapes is a backend property; shared-rung chunks
    stay bit-identical, pinned in tests); the bf16 quality gate
    (multiclass accuracy vs the f32 oracle, evaluation/ metrics) stays
    within its declared tolerance or ``qualify()`` refuses; zero
    post-warmup compiles on every engine; and the planner's evidence
    (per-bucket planned bytes, provenance, trims) rides the row."""
    from keystone_tpu.utils.metrics import CompileEventCounter
    from keystone_tpu.workflow.serving import CompiledPipeline

    d, features, classes = args.d, args.features, args.classes
    provisioned = args.provisioned_max or 4 * args.max_batch
    rng = np.random.default_rng(args.seed)
    sizes = rng.integers(1, args.max_batch + 1, size=args.requests)
    trace = [
        rng.normal(size=(int(n), d)).astype(np.float32) for n in sizes
    ]
    rows = int(sizes.sum())
    chain, X_eval, y_eval = build_trained_chain(
        d, features, classes, args.seed
    )
    compile_events = CompileEventCounter()

    def serve_phase(cp):
        cp.warmup((d,))
        ev0 = compile_events.count
        lats, outs = [], []
        t0 = time.perf_counter()
        for x in trace:
            t1 = time.perf_counter()
            outs.append(cp(x))
            lats.append(time.perf_counter() - t1)
        wall = time.perf_counter() - t0
        return {
            "lats": lats,
            "wall": wall,
            "outs": outs,
            "post_warmup_compiles": compile_events.count - ev0,
            "stats": cp.stats(),
        }

    # -- baseline: f32, hand-picked single provisioned-max bucket (every
    # request pads to the bucket someone sized for the biggest batch
    # they could imagine).
    base = serve_phase(CompiledPipeline(
        chain, buckets=[provisioned], devices=1, precision="f32",
        name="prec-handpicked-f32",
    ))
    # -- planned ladder, knob off: must be bit-identical to the baseline.
    planned = serve_phase(CompiledPipeline(
        chain, max_batch=provisioned, devices=1, precision="f32",
        name="prec-planned-f32",
    ))
    # -- knob-off contract: the engine built WITHOUT the precision knob
    # (today's construction) must serve bit-identically to the explicit
    # f32 engine on the same ladder — the default path is untouched.
    cp_default = CompiledPipeline(
        chain, max_batch=provisioned, devices=1,
        name="prec-planned-default",
    ).warmup((d,))
    bit_identical = all(
        np.array_equal(cp_default(x), out)
        for x, out in zip(trace, planned["outs"])
    )
    # Cross-ladder agreement is NUMERIC, not bit-level: a different
    # bucket shape legitimately changes gemm tiling (reduction order)
    # on some backends, so the evidence is the max relative error.
    ladder_rel_err = max(
        float(np.abs(a - b).max() / max(np.abs(a).max(), 1e-12))
        for a, b in zip(base["outs"], planned["outs"])
    )
    # -- planned ladder + bf16: the throughput mode under quality gates.
    cp_bf16 = CompiledPipeline(
        chain, max_batch=provisioned, devices=1, precision="bf16",
        name="prec-planned-bf16",
    )
    quality = cp_bf16.qualify(
        X_eval, y=y_eval, metric="multiclass",
        tolerance=args.quality_tolerance,
    )
    bf16 = serve_phase(cp_bf16)
    base_p99 = nearest_rank_ms(base["lats"], 99)
    bf16_p99 = nearest_rank_ms(bf16["lats"], 99)
    plan = planned["stats"]["plan"]
    result = {
        "metric": "serve_precision",
        "unit": "ms",
        "requests": args.requests,
        "rows": rows,
        "d": d,
        "features": features,
        "classes": classes,
        "provisioned_max": provisioned,
        "handpicked_ladder": base["stats"]["ladder"],
        "planned_ladder": planned["stats"]["ladder"],
        "plan": plan,
        "precision": "bf16",
        "quality": quality,
        "handpicked_f32": {
            **lat_stats(base["lats"]),
            "rows_per_s": round(rows / base["wall"], 1),
            "pad_rows_per_request": round(
                sum(provisioned - s for s in sizes) / len(sizes), 1
            ),
            "post_warmup_compiles": base["post_warmup_compiles"],
        },
        "planned_f32": {
            **lat_stats(planned["lats"]),
            "rows_per_s": round(rows / planned["wall"], 1),
            "post_warmup_compiles": planned["post_warmup_compiles"],
        },
        "planned_bf16": {
            **lat_stats(bf16["lats"]),
            "rows_per_s": round(rows / bf16["wall"], 1),
            "post_warmup_compiles": bf16["post_warmup_compiles"],
        },
        "speedup": {
            # "throughput" (wall ratio), matching the main serve row's
            # leaf naming — "wall" is a lower-better fragment in
            # bench_watch, and a speedup must judge higher-better.
            "throughput": round(base["wall"] / bf16["wall"], 2),
            "p99": round(base_p99 / bf16_p99, 2),
            "throughput_planned_f32": round(
                base["wall"] / planned["wall"], 2
            ),
        },
        "bit_identical_f32": bit_identical,
        "ladder_change_max_rel_err": ladder_rel_err,
        "pass": {
            # Structural pad-overhead win: hard on every backend.
            "wall_speedup_ge_1p5": base["wall"] / bf16["wall"] >= 1.5,
            "p99_speedup_ge_1p5": base_p99 / bf16_p99 >= 1.5,
            "bit_identical_when_knob_off": bit_identical,
            # A ladder change must not move answers beyond float noise
            # (bit-identity across DIFFERENT bucket shapes is a backend
            # property — gemm tiling follows the batch dim; shared-rung
            # chunks stay bit-identical, pinned in tests).
            "ladder_change_within_noise": ladder_rel_err <= 1e-5,
            "quality_within_tolerance": quality["within_tolerance"],
            "planner_ran": bool(plan and plan.get("enabled")),
            "zero_post_warmup_compiles": (
                base["post_warmup_compiles"] == 0
                and planned["post_warmup_compiles"] == 0
                and bf16["post_warmup_compiles"] == 0
            ),
        },
    }
    result["ok"] = all(result["pass"].values())
    return result


def run_replica_bench(args) -> dict:
    """Replica-pool scaling: serve the same uniform mixed-size trace at
    devices=1 and devices=N through the pipelined micro-batcher, with
    concurrent closed-loop clients keeping the dispatcher fed."""
    import jax

    from keystone_tpu.utils.metrics import environment_fingerprint
    from keystone_tpu.workflow.serving import CompiledPipeline, PipelineService

    n_local = len(jax.local_devices())
    if args.devices > n_local:
        raise SystemExit(
            f"--devices {args.devices} exceeds the {n_local} local devices "
            "(force more with --xla_force_host_platform_device_count)"
        )
    counts = sorted({1, args.devices})
    rng = np.random.default_rng(args.seed)
    sizes = rng.integers(1, args.max_batch + 1, size=args.requests)
    trace = [
        rng.normal(size=(int(n), args.d)).astype(np.float32) for n in sizes
    ]
    rows = int(sizes.sum())
    clients = max(1, args.service_clients)

    per_devices = {}
    single_outputs = None
    for c in counts:
        cp = CompiledPipeline(
            build_chain(args.d, args.features, args.classes, args.seed),
            max_batch=args.max_batch,
            devices=c,
            inflight=args.inflight,
        )
        cp.warmup((args.d,))
        # Bit-identity evidence: every request's output from the pool must
        # equal the single-device engine's, bit for bit (same XLA program,
        # same device kind — padding and replica choice must not matter).
        outputs = [cp(x) for x in trace]
        if single_outputs is None:
            single_outputs = outputs
            outputs_match = True
        else:
            outputs_match = all(
                np.array_equal(a, b)
                for a, b in zip(single_outputs, outputs)
            )
        # Balance is gated on the SERVICE phase alone: snapshot the
        # cumulative dispatch counters so the (uniformly round-robined)
        # bit-identity pass above can't mask a skewed dispatcher.
        pre_dispatch = dict(cp.stats()["replica_dispatches"])
        # Throughput: closed-loop clients × the shared trace through the
        # service — ~`clients` groups outstanding keeps >1 replica busy.
        errs: list = []

        def client(cid: int, svc):
            try:
                for i in range(cid, len(trace), clients):
                    svc.submit(trace[i]).result(timeout=120)
            except Exception as e:  # pragma: no cover - surfaced below
                errs.append(e)

        with PipelineService(
            cp, max_delay_ms=0.5, inflight=args.inflight
        ) as svc:
            threads = [
                threading.Thread(target=client, args=(k, svc))
                for k in range(clients)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            stats = svc.stats()
        if errs:
            raise errs[0]
        dispatches = {
            k: v - pre_dispatch.get(k, 0)
            for k, v in stats["compiled"]["replica_dispatches"].items()
        }
        served = {k: v for k, v in dispatches.items() if v > 0}
        balance = (
            max(dispatches.values()) / max(1, min(dispatches.values()))
            if dispatches else None
        )
        per_devices[str(c)] = {
            "devices": c,
            "wall_s": round(wall, 3),
            "rows_per_s": round(rows / wall, 1),
            "dispatch_balance": dispatches,
            "balance_max_over_min": (
                round(balance, 2) if balance is not None else None
            ),
            "replicas_serving": len(served),
            "outputs_match_single_device": outputs_match,
            "batches_run": stats["batches_run"],
            "replica_deaths": stats["replicas"]["deaths"],
            "latency": stats["latency"],
        }

    lo, hi = str(counts[0]), str(counts[-1])
    compared = counts[0] != counts[-1]
    speedup = (
        per_devices[hi]["rows_per_s"] / per_devices[lo]["rows_per_s"]
        if compared else 1.0
    )
    cores = os.cpu_count() or 1
    # One core can't run two replicas at once: the hard scaling gate only
    # binds on multi-core hosts; single-core merely must not regress. A
    # --devices 1 run compares nothing, so no gate applies at all.
    threshold = (1.3 if cores >= 2 else 0.75) if compared else None
    top = per_devices[hi]
    return {
        "metric": "serve_replica_scaling",
        "host_cores": cores,
        "env": environment_fingerprint(),
        "requests": args.requests,
        "rows": rows,
        "d": args.d,
        "features": args.features,
        "classes": args.classes,
        "clients": clients,
        "inflight": args.inflight,
        "devices_swept": counts,
        "per_devices": per_devices,
        "speedup_vs_single": round(speedup, 2),
        "speedup_threshold": threshold,
        "pass": {
            "outputs_bit_identical": all(
                e["outputs_match_single_device"]
                for e in per_devices.values()
            ),
            "every_replica_served": (
                top["replicas_serving"] == counts[-1]
            ),
            "balance_max_min_le_3x": (
                top["balance_max_over_min"] is not None
                and top["balance_max_over_min"] <= 3.0
            ),
            "throughput_gate": (
                speedup >= threshold if compared else None
            ),
            "throughput_gate_is_hard": compared and cores >= 2,
        },
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=160,
                    help="requests in the mixed-size trace")
    ap.add_argument("--max-batch", type=int, default=256,
                    help="largest request row count / top serving bucket")
    ap.add_argument("--d", type=int, default=64, help="input feature dim")
    ap.add_argument("--features", type=int, default=512,
                    help="random-feature width of the serving head")
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--service-clients", type=int, default=4,
                    help="concurrent single-row clients for the "
                    "micro-batcher phase (0 skips it)")
    ap.add_argument("--service-requests", type=int, default=200,
                    help="total single-row requests across clients")
    ap.add_argument("--out", type=str, default=None,
                    help="also write the JSON result to this path")
    ap.add_argument("--overload", action="store_true",
                    help="run the hardening bench instead: 2x sustained "
                    "over-capacity against a bounded queue + deadlines")
    ap.add_argument("--overload-seconds", type=float, default=3.0)
    ap.add_argument("--calibrate-seconds", type=float, default=1.5)
    ap.add_argument("--overload-max-pending", type=int, default=32)
    ap.add_argument("--overload-deadline-ms", type=float, default=100.0)
    ap.add_argument("--overload-max-rows", type=int, default=4,
                    help="rows per service flush in the overload phase — "
                    "the capacity-limited-device stand-in")
    ap.add_argument("--precision", action="store_true",
                    help="run the memory-bounded precision bench instead: "
                    "f32 hand-picked single-bucket ladder vs HBM-planned "
                    "ladder + bf16 under the evaluation/ quality gate")
    ap.add_argument("--provisioned-max", type=int, default=0,
                    help="the hand-picked baseline's provisioned bucket "
                    "(0 = 4x --max-batch): the pad-everything-to-max "
                    "config the planner replaces")
    ap.add_argument("--quality-tolerance", type=float, default=None,
                    help="override the declared quality-gate tolerance "
                    "(default: serving.PRECISION_QUALITY_TOLERANCES)")
    ap.add_argument("--daemon", action="store_true",
                    help="run the networked-daemon bench instead: open-loop "
                    "load at 2x capacity through the REAL socket ingress, "
                    "gold-tier p99 under deadline, two hot-swaps under load")
    ap.add_argument("--telemetry", action="store_true",
                    help="run the telemetry-overhead bench instead: the "
                    "same closed-loop socket flood with durable journey "
                    "export off vs on, gated on bounded throughput "
                    "overhead + the drops-counted-never-blocks contract")
    ap.add_argument("--telemetry-seconds", type=float, default=2.0)
    ap.add_argument("--telemetry-overhead-bound", type=float, default=0.30,
                    help="max allowed fractional req/s loss with durable "
                    "export on (the writer thread is off the hot path, "
                    "but 1-core CI hosts pay real scheduler tax)")
    ap.add_argument("--devices", type=int, default=0,
                    help="run the replica-scaling bench instead: serve the "
                    "trace at devices=1 and devices=N, report throughput + "
                    "dispatch balance (0 = off)")
    ap.add_argument("--inflight", type=int, default=2,
                    help="per-replica in-flight window for the replica "
                    "bench's pipelined dispatch")
    args = ap.parse_args()

    from keystone_tpu.utils.platform import ensure_live_backend

    backend = ensure_live_backend()
    import jax

    from keystone_tpu.config import config
    from keystone_tpu.utils.metrics import (
        CompileEventCounter,
        environment_fingerprint,
        maybe_trace,
        metrics_registry,
    )
    from keystone_tpu.workflow.serving import (
        CompiledPipeline,
        PipelineService,
        _jit_cache_size,
    )

    # The baseline phase must measure TRUE per-shape jit: an inherited
    # KEYSTONE_SERVE_BUCKETS would silently route batch_call through
    # bucketing and collapse the comparison to bucketed-vs-bucketed.
    # The env var must go too, not just the config snapshot: the ladder
    # resolution reads it LIVE (env-pins-win), so an exported value
    # would pin every engine's ladder — hard-failing the --precision
    # mode's planner_ran gate and turning its "planned ladder" column
    # into the operator's env ladder (the KEYSTONE_PROFILE_STORE bench
    # isolation precedent). Same for an ambient serving precision: the
    # A/B names its precision per engine explicitly, and the knob-off
    # phase must really be the default f32 path.
    os.environ.pop("KEYSTONE_SERVE_BUCKETS", None)
    os.environ.pop("KEYSTONE_SERVE_PRECISION", None)
    config.serve_buckets = ()
    config.serve_precision = "f32"
    # Same class: an ambient KEYSTONE_PLAN_RESOURCES=0 (the documented
    # programmatic-pin workaround) snapshots config.plan_resources False
    # at import and would hard-fail the --precision planner_ran gate.
    config.plan_resources = True

    if args.precision:
        with maybe_trace("bench_serve_precision"):
            result = run_precision_bench(args)
        result["backend"] = backend
        result["host_cores"] = os.cpu_count()
        result["env"] = environment_fingerprint()
        line = json.dumps(result)
        print(line)
        if args.out:
            write_result(args.out, line, result["metric"])
        sys.exit(0 if result["ok"] else 1)

    if args.daemon:
        with maybe_trace("bench_serve_daemon"):
            result = run_daemon_bench(args)
        result["backend"] = backend
        result["host_cores"] = os.cpu_count()
        result["env"] = environment_fingerprint()
        line = json.dumps(result)
        print(line)
        if args.out:
            write_result(args.out, line, result["metric"])
        sys.exit(0 if result["ok"] else 1)

    if args.telemetry:
        with maybe_trace("bench_serve_telemetry"):
            result = run_telemetry_bench(args)
        result["backend"] = backend
        result["host_cores"] = os.cpu_count()
        result["env"] = environment_fingerprint()
        line = json.dumps(result)
        print(line)
        if args.out:
            write_result(args.out, line, result["metric"])
        sys.exit(0 if result["ok"] else 1)

    if args.devices > 0:
        with maybe_trace("bench_serve_replicas"):
            result = run_replica_bench(args)
        result["backend"] = backend
        line = json.dumps(result)
        print(line)
        if args.out:
            # The scaling row lives next to the main serving anchor;
            # reruns replace only their own metric's row.
            write_result(args.out, line, result["metric"])
        return

    if args.overload:
        cp = CompiledPipeline(
            build_chain(args.d, args.features, args.classes, args.seed),
            max_batch=args.max_batch,
        )
        cp.warmup((args.d,))
        # KEYSTONE_PROFILE_DIR=... additionally captures a jax profiler
        # trace of the overload run, no code edits needed.
        with maybe_trace("bench_serve_overload"):
            overload = run_overload(cp, args)
        result = {
            "metric": "serve_overload",
            "backend": backend,
            "host_cores": os.cpu_count(),
            "env": environment_fingerprint(),
            "d": args.d,
            "features": args.features,
            "classes": args.classes,
            "ladder": list(cp.ladder),
            "overload": overload,
        }
        line = json.dumps(result)
        print(line)
        if args.out:
            write_result(args.out, line, result["metric"])
        return

    compile_events = CompileEventCounter()
    rng = np.random.default_rng(args.seed)
    sizes = rng.integers(1, args.max_batch + 1, size=args.requests)
    trace = [
        rng.normal(size=(int(n), args.d)).astype(np.float32) for n in sizes
    ]

    # KEYSTONE_PROFILE_DIR=... captures a jax profiler trace of both
    # serving phases alongside the timing, no code edits needed.
    with maybe_trace("bench_serve"):
        # -- naive: per-shape jit ---------------------------------------------
        naive = build_chain(args.d, args.features, args.classes, args.seed)
        # One warm call at the top size — the naive server has seen SOME
        # traffic; every new row count in the trace still recompiles.
        jax.block_until_ready(naive.batch_call(trace[0][: args.max_batch]))
        ev0 = compile_events.count
        naive_lats = []
        t0 = time.perf_counter()
        for x in trace:
            t1 = time.perf_counter()
            jax.block_until_ready(naive.batch_call(x))
            naive_lats.append(time.perf_counter() - t1)
        naive_wall = time.perf_counter() - t0
        naive_compiles = compile_events.count - ev0

        # -- bucketed + AOT warmup --------------------------------------------
        # One registry reset covers the serving counters AND the
        # request-latency histogram the bucketed phase is about to fill.
        metrics_registry.reset()
        cp = CompiledPipeline(
            build_chain(args.d, args.features, args.classes, args.seed),
            max_batch=args.max_batch,
        )
        ev0 = compile_events.count
        cp.warmup((args.d,))
        warmup_compiles = compile_events.count - ev0
        ev0 = compile_events.count
        bucketed_lats = []
        t0 = time.perf_counter()
        for x in trace:
            t1 = time.perf_counter()
            cp(x)  # host-out: the np result is already synchronized
            bucketed_lats.append(time.perf_counter() - t1)
        bucketed_wall = time.perf_counter() - t0
        post_warmup_compiles = compile_events.count - ev0

    rows = int(sizes.sum())
    naive_p99 = float(np.percentile(np.asarray(naive_lats) * 1e3, 99))
    bucketed_p99 = float(np.percentile(np.asarray(bucketed_lats) * 1e3, 99))
    # The unified registry is THE counter source — one snapshot feeds the
    # serving counters and the internal latency histogram (which must
    # agree with this bench's own external timing within 10%).
    registry_snap = metrics_registry.snapshot()
    counters = registry_snap["serving"]
    reg_lat = registry_snap["serve.request_latency"]

    result = {
        "metric": "serve_bucketed_vs_pershape",
        "backend": backend,
        "host_cores": os.cpu_count(),
        "env": environment_fingerprint(),
        "requests": args.requests,
        "rows": rows,
        "d": args.d,
        "features": args.features,
        "classes": args.classes,
        "ladder": list(cp.ladder),
        "naive": {
            **lat_stats(naive_lats),
            "rows_per_s": round(rows / naive_wall, 1),
            "compiles": naive_compiles,
            "jit_cache_entries": _jit_cache_size(naive._jitted()),
        },
        "bucketed": {
            **lat_stats(bucketed_lats),
            "rows_per_s": round(rows / bucketed_wall, 1),
            "warmup_seconds": round(cp.warmup_seconds, 3),
            "warmup_compiles": warmup_compiles,
            "post_warmup_compiles": post_warmup_compiles,
            "serving_counter_compiles_post_warmup": (
                counters["compiles"] - len(cp.ladder)
            ),
            "compiles_by_bucket": counters["compiles_by_bucket"],
            "pad_overhead": round(counters["pad_overhead"], 4),
            "bucket_hits": counters["bucket_hits"],
        },
        "registry_latency": {
            # MetricsRegistry's internal histogram vs this bench's own
            # external stopwatch over the same requests: the acceptance
            # contract is agreement within 10%, nearest-rank on both sides
            # (see nearest_rank_ms).
            **reg_lat,
            "p50_vs_external": round(
                reg_lat["p50_ms"] / nearest_rank_ms(bucketed_lats, 50), 3
            ),
            "p99_vs_external": round(
                reg_lat["p99_ms"] / nearest_rank_ms(bucketed_lats, 99), 3
            ),
        },
        "speedup": {
            "p50": round(
                float(np.percentile(np.asarray(naive_lats) * 1e3, 50))
                / float(np.percentile(np.asarray(bucketed_lats) * 1e3, 50)),
                2,
            ),
            "p99": round(naive_p99 / bucketed_p99, 2),
            "throughput": round(naive_wall / bucketed_wall, 2),
        },
        "pass": {
            "zero_post_warmup_compiles": post_warmup_compiles == 0,
            "p99_speedup_ge_2x": naive_p99 / bucketed_p99 >= 2.0,
            "registry_p99_within_10pct": (
                abs(reg_lat["p99_ms"] / nearest_rank_ms(bucketed_lats, 99)
                    - 1.0) <= 0.10
            ),
        },
    }

    # -- micro-batcher: concurrent single-row clients -------------------------
    if args.service_clients > 0:
        per_client = max(1, args.service_requests // args.service_clients)
        lats, lock = [], threading.Lock()

        def client(cid: int):
            crng = np.random.default_rng(1000 + cid)
            mine = []
            for _ in range(per_client):
                x = crng.normal(size=(args.d,)).astype(np.float32)
                t1 = time.perf_counter()
                svc.submit(x).result()
                mine.append(time.perf_counter() - t1)
            with lock:
                lats.extend(mine)

        with PipelineService(cp, max_delay_ms=2.0) as svc:
            threads = [
                threading.Thread(target=client, args=(c,))
                for c in range(args.service_clients)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            svc_wall = time.perf_counter() - t0
            stats = svc.stats()
        result["service"] = {
            **lat_stats(lats),
            "clients": args.service_clients,
            "requests": stats["requests"],
            "device_batches": stats["batches_run"],
            "coalesce_ratio": round(stats["coalesce_ratio"], 2),
            "rows_per_s": round(stats["rows_served"] / svc_wall, 1),
            # The service's own registry-backed e2e histogram, next to the
            # client-side stopwatch numbers above.
            "internal_latency": stats["latency"],
        }

    line = json.dumps(result)
    print(line)
    if args.out:
        write_result(args.out, line, result["metric"])


if __name__ == "__main__":
    main()
