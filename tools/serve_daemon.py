"""Run (or smoke-test) the networked serving daemon.

The operational entry point for ``workflow/daemon.py``: load a versioned
model artifact (``workflow/serialization.py save_artifact``) and serve
it over HTTP/JSON + the length-prefixed socket, with tenant admission
control and zero-downtime hot-swap (``POST /swap``).

Usage:
    # serve an exported artifact until interrupted
    python tools/serve_daemon.py --artifact model.kart --port 8700

    # the `make serve-daemon` smoke: export two demo artifacts, stand up
    # a live daemon, drive both ingresses, verify admission (403/429),
    # healthz generation identity, and a hot-swap UNDER TRAFFIC with
    # zero dropped requests and per-generation bit-identity; exits 0/1.
    python tools/serve_daemon.py --smoke

Wire protocol and knob reference: README "Serving over the network".
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import struct
import sys
import threading
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_demo_pipeline(d: int, seed: int):
    """A small fitted serving chain whose outputs differ per seed — two
    seeds = two distinguishable model generations."""
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures

    return (
        CosineRandomFeatures.create(d, 32, seed=seed)
        .and_then(L2Normalizer())
        .fit()
    )


def http_post(port: int, path: str, body: dict, headers=None, timeout=30,
              retries: int = 4):
    """POST JSON; returns (status, parsed body). stdlib only.

    Retries on connection-level failures (the daemon's ``conn_drop``
    fault site drops the response after serving — the serve chain is
    pure, so re-sending is safe and is exactly what a real client
    does)."""
    import http.client

    last: Exception = ConnectionError("no attempt made")
    for _attempt in range(max(1, retries)):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json", **(headers or {})},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())
        except (http.client.HTTPException, OSError) as e:
            # Dropped connection (incl. urllib.error.URLError): retry.
            last = e
    raise last


def http_get(port: int, path: str, timeout=30):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout
        ) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class SocketClient:
    """Length-prefixed framed client for the daemon's socket ingress."""

    def __init__(self, port: int, timeout: float = 30.0):
        self._conn = socket.create_connection(("127.0.0.1", port),
                                              timeout=timeout)

    def request(self, doc: dict) -> dict:
        frame = json.dumps(doc).encode()
        self._conn.sendall(struct.pack(">I", len(frame)) + frame)
        header = self._recv_exact(4)
        (length,) = struct.unpack(">I", header)
        return json.loads(self._recv_exact(length))

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        got = 0
        while got < n:
            chunk = self._conn.recv(n - got)
            if not chunk:
                raise ConnectionError("daemon closed the connection")
            chunks.append(chunk)
            got += len(chunk)
        return b"".join(chunks)

    def close(self):
        try:
            self._conn.close()
        except OSError:
            pass


def run_smoke(d: int = 8, requests: int = 24, out_dir=None) -> dict:
    """The ``make serve-daemon`` flow (also run in-process by
    tests/test_daemon.py): live daemon, both ingresses, admission,
    healthz identity, hot-swap under traffic. Returns a verdict dict."""
    import tempfile

    import numpy as np

    from keystone_tpu.workflow.daemon import ServingDaemon, Tenant
    from keystone_tpu.workflow.serialization import save_artifact

    out_dir = out_dir or tempfile.mkdtemp(prefix="keystone_daemon_smoke_")
    p1 = _build_demo_pipeline(d, seed=0)
    p2 = _build_demo_pipeline(d, seed=1)
    a1 = os.path.join(out_dir, "model_v1.kart")
    a2 = os.path.join(out_dir, "model_v2.kart")
    art1 = save_artifact(p1, a1, feature_shape=(d,), dtype="float32")
    art2 = save_artifact(p2, a2, feature_shape=(d,), dtype="float32")

    tenants = {
        "sk-gold": Tenant("gold-tenant", "sk-gold", qps=10000, tier="gold"),
        "sk-be": Tenant("be-tenant", "sk-be", qps=2, tier="best_effort"),
    }
    rng = np.random.default_rng(0)
    X = rng.normal(size=(4, d)).astype(np.float32)
    ref1 = np.asarray(p1.apply(X).get())
    ref2 = np.asarray(p2.apply(X).get())

    daemon = ServingDaemon(
        artifact=a1, tenants=tenants, devices=1, buckets=(4, 8),
        max_delay_ms=1.0, name="smoke-daemon", gold_deadline_ms=30000,
        swap_token="smoke-swap-token",
    )
    stop = threading.Event()
    traffic_results: list = []
    traffic_errors: list = []

    def traffic():
        # Sustained gold traffic across the swap: every request must get
        # an answer attributable to exactly one generation. An exhausted
        # retry raise is recorded as an error, not a silent thread death
        # — a dead traffic thread would false-green the very
        # zero-dropped gate this smoke exists to check.
        while not stop.is_set():
            try:
                st, doc = http_post(
                    daemon.http_port, "/predict",
                    {"x": X.tolist()}, {"X-API-Key": "sk-gold"},
                )
            except (ConnectionError, TimeoutError, OSError) as e:
                traffic_errors.append(("exc", type(e).__name__))
                continue
            if st == 200:
                traffic_results.append(
                    (doc["generation"],
                     np.asarray(doc["y"], dtype=np.float32))
                )
            else:
                traffic_errors.append((st, doc.get("error")))

    try:
        st0, doc0 = http_post(
            daemon.http_port, "/predict", {"x": X.tolist()},
            {"X-API-Key": "sk-gold", "X-Trace-Id": "smoke-trace-http"},
        )
        http_ok = st0 == 200 and np.array_equal(
            np.asarray(doc0["y"], np.float32), ref1
        )
        # Wire trace context round-trips both ingresses: the id the
        # client sent comes back on its response (and names the daemon
        # journey — tests/test_daemon.py pins that leg).
        http_trace_ok = doc0.get("trace_id") == "smoke-trace-http"
        sresp = None
        for _ in range(4):  # reconnect-and-retry across injected drops
            sc = SocketClient(daemon.socket_port)
            try:
                sresp = sc.request({"x": X.tolist(), "key": "sk-gold",
                                    "trace_id": "smoke-trace-sock"})
                break
            except (ConnectionError, OSError):
                continue
            finally:
                sc.close()
        socket_ok = (
            sresp is not None and sresp["status"] == 200
            and np.array_equal(np.asarray(sresp["y"], np.float32), ref1)
        )
        socket_trace_ok = (
            sresp is not None
            and sresp.get("trace_id") == "smoke-trace-sock"
        )
        auth_status = http_post(
            daemon.http_port, "/predict", {"x": X.tolist()}
        )[0]
        be_codes = [
            http_post(daemon.http_port, "/predict", {"x": X.tolist()},
                      {"X-API-Key": "sk-be"})[0]
            for _ in range(6)
        ]
        h_st, h_body = http_get(daemon.http_port, "/healthz")
        health = json.loads(h_body)
        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        # Control plane is token-locked when tenants are configured: a
        # data-plane key must not swap the model.
        swap_denied = http_post(
            daemon.http_port, "/swap", {"artifact": a2}, timeout=120,
            retries=1,
        )[0]
        # retries=1: /swap is NOT idempotent — a retried ack-lost swap
        # would run twice and land one generation past the expectation.
        swap_st, swap_doc = http_post(
            daemon.http_port, "/swap", {"artifact": a2},
            {"X-Swap-Token": "smoke-swap-token"}, timeout=120,
            retries=1,
        )
        # A few post-swap responses, then stop.
        for _ in range(max(4, requests // 4)):
            http_post(daemon.http_port, "/predict", {"x": X.tolist()},
                      {"X-API-Key": "sk-gold"})
        stop.set()
        t.join(timeout=60)
        h2_st, h2_body = http_get(daemon.http_port, "/healthz")
        health2 = json.loads(h2_body)
        gen_attribution_ok = True
        for gen, y in traffic_results:
            expect = ref1 if gen == 0 else ref2
            if not np.array_equal(y, expect):
                gen_attribution_ok = False
        gens = sorted({g for g, _ in traffic_results})
        stats = daemon.stats()
        result = {
            "metric": "serve_daemon_smoke",
            "http_port": daemon.http_port,
            "socket_port": daemon.socket_port,
            "fingerprints": [art1.fingerprint, art2.fingerprint],
            "traffic_responses": len(traffic_results),
            "traffic_errors": traffic_errors[:10],
            "generations_seen": gens,
            "be_codes": be_codes,
            "pass": {
                "http_bit_identical": bool(http_ok),
                "socket_bit_identical": bool(socket_ok),
                "trace_id_http_echo": bool(http_trace_ok),
                "trace_id_socket_echo": bool(socket_trace_ok),
                "auth_403": auth_status == 403,
                "quota_429": 429 in be_codes,
                "swap_tokenless_403": swap_denied == 403,
                "healthz_identity": (
                    h_st == 200
                    and health.get("generation") == 0
                    and health.get("artifact_fingerprint")
                    == art1.fingerprint
                    and health.get("draining") is False
                ),
                "swap_200": swap_st == 200
                and swap_doc.get("generation") == 1,
                "healthz_post_swap": h2_st == 200
                and health2.get("generation") == 1
                and health2.get("artifact_fingerprint") == art2.fingerprint,
                "zero_dropped_under_swap": not traffic_errors,
                "generation_attribution": gen_attribution_ok
                and len(gens) >= 1,
                "zero_active_leftover": stats["active_requests"] == 0,
            },
        }
        result["ok"] = all(result["pass"].values())
        return result
    finally:
        daemon.close()


def main(argv=None) -> int:
    from keystone_tpu.config import config

    ap = argparse.ArgumentParser()
    ap.add_argument("--artifact", help="model artifact path (save_artifact)")
    ap.add_argument("--host", default=None,
                    help="bind address for both ingresses (default "
                         "KEYSTONE_SERVE_HOST = 127.0.0.1; 0.0.0.0 to "
                         "serve external traffic)")
    ap.add_argument("--port", type=int, default=None,
                    help="HTTP ingress port (default KEYSTONE_SERVE_PORT; "
                         "0 = ephemeral)")
    ap.add_argument("--socket-port", type=int, default=None,
                    help="framed-socket ingress port "
                         "(default KEYSTONE_SERVE_SOCKET_PORT)")
    ap.add_argument("--devices", type=int, default=None,
                    help="replica pool width (default "
                         "KEYSTONE_SERVE_DEVICES)")
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--smoke", action="store_true",
                    help="run the live end-to-end smoke and exit 0/1")
    args = ap.parse_args(argv)

    if args.smoke:
        result = run_smoke()
        print(json.dumps(result))
        if result["ok"]:
            print("serve-daemon smoke: PASS", file=sys.stderr)
        else:
            failed = [k for k, v in result["pass"].items() if not v]
            print(f"serve-daemon smoke: FAIL {failed}", file=sys.stderr)
        return 0 if result["ok"] else 1

    if not args.artifact:
        print("--artifact is required (or use --smoke)", file=sys.stderr)
        return 2

    from keystone_tpu.workflow.daemon import ServingDaemon

    daemon = ServingDaemon(
        artifact=args.artifact,
        host=args.host,
        http_port=args.port,
        socket_port=args.socket_port,
        devices=args.devices,
        max_batch=args.max_batch,
    )
    tenant_mode = (
        "open (no tenants)" if not config.tenants
        else f"{len(config.tenants.split(','))} tenant(s)"
    )
    print(
        f"serving generation {daemon.generation} "
        f"(artifact {daemon.artifact_fingerprint[:12]}) on "
        f"http://{daemon.host}:{daemon.http_port} + "
        f"socket {daemon.host}:{daemon.socket_port} — {tenant_mode}; "
        "POST /swap to hot-swap; Ctrl-C to stop",
        file=sys.stderr,
    )
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        daemon.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
