"""Profile-guided optimizer A/B bench: optimizer-off vs optimizer-on.

The ISSUE-12 tentpole gate, measured end-to-end: two canonical pipeline
shapes are fitted-and-applied twice each —

- **optimizer-off**: ``config.auto_cache = False`` (the default) — the
  whole-pipeline rules never rewrite the graph, every apply recomputes
  the featurizer chain;
- **optimizer-on**: a prior ``Pipeline.fit(profile=True)`` persisted the
  MEASURED per-node profile to the store, then ``config.auto_cache =
  True`` lets ``AutoCacheRule`` consume it — pricing cache insertions
  from measured wall/bytes with ZERO sample-run executions (counted and
  gated), so later applies hit the session cache instead of recomputing.

The two shapes (both host-heavy with FIXED iteration counts, so outputs
are deterministic and the bit-identity gate is exact):

- ``reused_subchain`` — ONE heavy featurizer prefix consumed by two
  branches (the KG202 shape): the optimizer inserts a cache above the
  fan-out;
- ``two_branch`` — two INDEPENDENT heavy featurizer branches gathered
  into one solve (the ImageNet SIFT|LCS shape): each branch earns its
  own cache, and ``PlanResourcesRule`` additionally plans the executor
  width (overlap on multi-core hosts; decision recorded either way).

Gates (hard, both pipelines — the cache win avoids recompute, so unlike
the worker-overlap bench it does NOT need a second core):

- predictions bit-identical between the arms (every timed apply);
- optimizer-on wall >= 1.2x faster than optimizer-off;
- zero sample-run executions in the optimizer-on arm (the measured
  profile replaced the 64-row ``Profiler`` run entirely).

The result row APPENDS to ``--out`` (BENCH_fit.json) as fingerprinted
JSONL history — ``make bench-watch`` fits noise bands over prior rows:
the speedup value regressing DOWN, wall leaves regressing UP, or the
``bit_identical`` / ``zero_sample_runs`` flags flipping false all fail
the gate.

Usage: python tools/bench_optimizer.py [--reps 3] [--applies 2]
           [--quick] [--out BENCH_fit.json]
Prints one JSON line (and the optimizer's decision table on stderr);
exit 1 on any failed hard gate.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_fit import HostFFTFeaturizer  # noqa: E402
from keystone_tpu.workflow.pipeline import Pipeline, Transformer  # noqa: E402


class ScaleBy(Transformer):
    """A trivially cheap jittable consumer: its only job is to fan the
    heavy prefix out to >= 2 consumers (the re-used-subchain shape)
    without contributing measurable work of its own."""

    jittable = True

    def __init__(self, c: float):
        self.c = float(c)

    def signature(self):
        return self.stable_signature(self.c)

    def apply_batch(self, X):
        return X * self.c


def build_reused_subchain(X, y, work_iters: int) -> Pipeline:
    """One heavy featurizer prefix shared by two consumer branches —
    the canonical KG202 advice shape, and the auto-cache rule's bread
    and butter: cache above the fan-out, recompute once."""
    from keystone_tpu.nodes.learning.linear_mapper import LinearMapEstimator

    prefix = HostFFTFeaturizer(seed=1, iters=work_iters).to_pipeline()
    b1 = prefix.and_then(ScaleBy(2.0))
    b2 = prefix.and_then(ScaleBy(0.5))
    return Pipeline.gather([b1, b2]).and_then(
        LinearMapEstimator(lam=1e-3), X, y
    )


def build_two_branch(X, y, work_iters: int) -> Pipeline:
    """Two independent heavy featurizer branches gathered into one solve
    — the two-branch ImageNet featurizer shape (bench_fit's pipeline):
    each branch earns its own cache from measured costs, and the
    resource planner sees a branch width of 2."""
    from keystone_tpu.nodes.learning.linear_mapper import LinearMapEstimator

    fronts = [
        HostFFTFeaturizer(seed=i + 1, iters=work_iters).to_pipeline()
        for i in range(2)
    ]
    return Pipeline.gather(fronts).and_then(
        LinearMapEstimator(lam=1e-3), X, y
    )


PIPELINES = {
    "reused_subchain": build_reused_subchain,
    "two_branch": build_two_branch,
}


def _arm(build, X_eval, applies: int, optimizer_on: bool):
    """One cold fit + ``applies`` applies under a fresh session. The
    optimizer plans at FIT time; applies run plain in both arms (they
    hit the session cache through the executor's discovery cut — the
    profile-once-optimize-forever protocol). Returns (wall s, preds)."""
    from keystone_tpu.config import config
    from keystone_tpu.workflow.executor import PipelineEnv

    PipelineEnv.reset()
    prev = config.auto_cache
    t0 = time.perf_counter()
    try:
        config.auto_cache = optimizer_on
        fitted = build().fit()
    finally:
        config.auto_cache = prev
    preds = [np.asarray(fitted.apply(X_eval).get()) for _ in range(applies)]
    wall = time.perf_counter() - t0
    PipelineEnv.reset()
    return wall, preds


def _count_sample_runs():
    """Install counting wrappers on BOTH Profiler entry points (the
    full profile() run and the shape-only sample_values() run); returns
    (counter dict, restore callable)."""
    from keystone_tpu.workflow.cache import Profiler

    calls = {"n": 0}
    orig_profile, orig_sample = Profiler.profile, Profiler.sample_values

    def counting_profile(self, *a, **k):
        calls["n"] += 1
        return orig_profile(self, *a, **k)

    def counting_sample(self, *a, **k):
        calls["n"] += 1
        return orig_sample(self, *a, **k)

    Profiler.profile = counting_profile
    Profiler.sample_values = counting_sample

    def restore():
        Profiler.profile = orig_profile
        Profiler.sample_values = orig_sample

    return calls, restore


def bench_pipeline(name: str, args) -> dict:
    """A/B one canonical pipeline; returns its detail dict."""
    from keystone_tpu.workflow import rules
    from keystone_tpu.workflow.executor import PipelineEnv

    rng = np.random.default_rng(0)
    n, d, k = args.rows, args.dim, args.classes
    X = rng.normal(size=(n, d)).astype(np.float32)
    W_true = rng.normal(size=(d, k)).astype(np.float32)
    y = (X @ W_true + 0.01 * rng.normal(size=(n, k))).astype(np.float32)
    # The timed applies score the TRAINING matrix — the canonical
    # repeated-reuse workload the inserted cache serves (training-set
    # predictions, residuals, CV passes over one featurization): the
    # session cache replays the fit-side subchain's value. Held-out rows
    # would execute the serve chain on fresh data, which no cache can
    # (or should) shortcut.
    X_eval = X

    def build():
        return PIPELINES[name](X, y, args.work_iters)

    store = tempfile.mkdtemp(prefix=f"keystone_bench_opt_{name}_")
    # Isolate via the ENV var, which wins over config.profile_store in
    # resolved_profile_store(): with a user-exported KEYSTONE_PROFILE_STORE
    # a config-level override would silently read/write the user's real
    # store and contaminate the A/B with stale entries.
    prev_env = os.environ.get("KEYSTONE_PROFILE_STORE")
    os.environ["KEYSTONE_PROFILE_STORE"] = store
    calls, restore = None, None
    try:
        # Profile once (untimed): the measured store entry the on-arm
        # consumes. This also eats the solver's first-in-process XLA
        # compiles, warming both arms equally.
        PipelineEnv.reset()
        profiled = build().fit(profile=True)
        saved = getattr(profiled, "fit_profile", None)
        store_entry = bool(saved is not None and saved.saved_to)

        # Untimed warmup of the off-arm path too (process jit caches).
        _arm(build, X_eval, 1, optimizer_on=False)

        off_walls, on_walls = [], []
        off_preds = on_preds = None
        calls, restore = _count_sample_runs()
        rules.clear_decisions()
        for _ in range(args.reps):
            wall, off_preds = _arm(build, X_eval, args.applies, False)
            off_walls.append(wall)
            wall, on_preds = _arm(build, X_eval, args.applies, True)
            on_walls.append(wall)
    finally:
        if restore is not None:
            restore()
        if prev_env is None:
            os.environ.pop("KEYSTONE_PROFILE_STORE", None)
        else:
            os.environ["KEYSTONE_PROFILE_STORE"] = prev_env
        PipelineEnv.reset()
        import shutil

        shutil.rmtree(store, ignore_errors=True)

    decisions = rules.optimizer_decisions()
    off_s = statistics.median(off_walls)
    on_s = statistics.median(on_walls)
    speedup = off_s / on_s if on_s > 0 else float("inf")
    bit_identical = bool(
        len(off_preds) == len(on_preds)
        and all(
            a.shape == b.shape and np.array_equal(a, b)
            for a, b in zip(off_preds, on_preds)
        )
    )
    return {
        "off_wall_s": round(off_s, 4),
        "on_wall_s": round(on_s, 4),
        "speedup": round(speedup, 3),
        "bit_identical": bit_identical,
        "sample_runs": calls["n"],
        "store_entry_saved": store_entry,
        "cache_inserts": sum(
            1 for dec in decisions if dec.action == "cache-insert"
        ),
        "measured_decisions": sum(
            1 for dec in decisions if dec.provenance == "measured"
        ),
        "_decisions": decisions,
    }


def run_bench(args) -> dict:
    import jax

    from keystone_tpu.utils.metrics import environment_fingerprint

    details = {}
    all_decisions = []
    for name in PIPELINES:
        det = bench_pipeline(name, args)
        all_decisions.extend(
            (name, dec) for dec in det.pop("_decisions")
        )
        details[name] = det

    speedups = [det["speedup"] for det in details.values()]
    bit_identical = all(det["bit_identical"] for det in details.values())
    zero_sample_runs = all(
        det["sample_runs"] == 0 for det in details.values()
    )
    speedup_gate = all(s >= args.min_speedup for s in speedups)

    row = {
        "metric": "fit_optimizer",
        "value": round(min(speedups), 3),
        "unit": "x speedup (optimizer-off wall / optimizer-on wall, "
                "worst pipeline)",
        "backend": jax.default_backend(),
        "host_cores": os.cpu_count() or 1,
        "env": environment_fingerprint(),
        "detail": {
            "pipelines": details,
            "reps": args.reps,
            "applies": args.applies,
            "work_iters": args.work_iters,
            "rows": args.rows,
            "dim": args.dim,
            "classes": args.classes,
            "min_speedup": args.min_speedup,
            "bit_identical": bit_identical,
            "zero_sample_runs": zero_sample_runs,
            "speedup_gate": speedup_gate,
        },
    }
    # --quick is harness validation: the tiny problem is mostly session
    # setup, so only bit-identity + zero-sample-runs are judged there.
    row["ok"] = bool(
        bit_identical
        and zero_sample_runs
        and (speedup_gate or getattr(args, "quick", False))
    )
    row["_decisions"] = all_decisions
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="profile-guided optimizer-off vs optimizer-on bench"
    )
    ap.add_argument("--reps", type=int, default=3,
                    help="A/B rounds per pipeline; median walls compared")
    ap.add_argument("--applies", type=int, default=2,
                    help="timed applies after each fit (the recompute the "
                         "inserted caches avoid)")
    ap.add_argument("--rows", type=int, default=512)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--work-iters", type=int, default=60,
                    help="FFT/tanh rounds per heavy featurizer (fixed "
                         "count: deterministic outputs)")
    ap.add_argument("--min-speedup", type=float, default=1.2,
                    help="hard wall-clock gate per pipeline")
    ap.add_argument("--quick", action="store_true",
                    help="tiny problem, 1 rep — harness validation only, "
                         "no row is written and the speedup gate is soft")
    ap.add_argument("--out", default=None,
                    help="append the fingerprinted JSONL row here")
    args = ap.parse_args(argv)

    if args.quick:
        args.rows, args.dim, args.classes = 96, 64, 4
        args.work_iters, args.reps, args.applies = 6, 1, 1

    row = run_bench(args)
    decisions = row.pop("_decisions")
    print(json.dumps(row), flush=True)

    # The explainability half: what the optimizer chose and why, straight
    # from the decision log profile_report.py --decisions renders.
    from profile_report import render_decision_table

    print("\n" + render_decision_table(
        [dec for _name, dec in decisions]
    ), file=sys.stderr)

    if args.out and not args.quick:
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")

    det = row["detail"]
    if not det["bit_identical"]:
        print("GATE FAILED: optimizer-on predictions differ from "
              "optimizer-off", file=sys.stderr)
        return 1
    if not det["zero_sample_runs"]:
        runs = {n: d["sample_runs"] for n, d in det["pipelines"].items()}
        print(f"GATE FAILED: sample runs executed on the measured path "
              f"({runs})", file=sys.stderr)
        return 1
    if not det["speedup_gate"] and not args.quick:
        print(
            f"GATE FAILED: optimizer-on speedup {row['value']}x < "
            f"{args.min_speedup}x on the worst pipeline "
            f"({ {n: d['speedup'] for n, d in det['pipelines'].items()} })",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
