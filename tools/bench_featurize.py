"""Featurization throughput benchmark — the CIFAR conv front end on TPU.

Ref: src/main/scala/pipelines/images/cifar/RandomPatchCifar.scala's
featurization stage (Convolver + SymmetricRectifier + Pooler; SURVEY.md
§3.1) [unverified] — the reference runs this as per-image im2col+gemm
`mapPartitions` over EC2 CPU cores; here the whole chain is ONE fused XLA
program on the MXU (`lax.conv_general_dilated` + vector rectify +
`reduce_window` pool), measured in images/sec and conv TFLOPS/chip.

NOTES_r2 clocked the same chain at ~129 img/s on this 1-core host CPU;
this tool produces the silicon number next to it. Timing discipline
mirrors bench.py: a warm-up compile rep, then a timed loop that forces a
device-to-host fetch of a reduction each rep (the axon relay has produced
impossible timings when nothing is fetched).

Usage: python tools/bench_featurize.py [--filters 1024] [--batch 2048]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def conv_flops(
    n: int, h: int, w: int, c: int, nf: int, fh: int, fw: int
) -> float:
    oh, ow = h - fh + 1, w - fw + 1
    return 2.0 * n * oh * ow * fh * fw * c * nf


def measure(batch: int, filters: int, dtype: str, reps: int) -> dict:
    import jax
    import jax.numpy as jnp

    from keystone_tpu.pipelines.images.random_patch_cifar import (
        RandomPatchCifarConfig,
        build_featurizer,
    )

    conf = RandomPatchCifarConfig(
        num_filters=filters,
        feature_dtype="bfloat16" if dtype == "bf16" else None,
        patch_sample=2048,
        synthetic_n=batch,
    )
    rng = np.random.default_rng(0)
    images = jnp.asarray(
        rng.uniform(size=(batch, 32, 32, 3)).astype(np.float32)
    )
    featurizer = build_featurizer(conf, images)

    def step(x):
        return featurizer(x).get()

    out = step(images)  # compile + warm-up
    feature_dim = int(np.prod(out.shape[1:]))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = step(images)
        # Force real device completion + transport each rep.
        float(jnp.sum(out[0]))
    dt = (time.perf_counter() - t0) / reps
    fl = conv_flops(batch, 32, 32, 3, filters, conf.patch_size, conf.patch_size)
    return {
        "batch": batch,
        "filters": filters,
        "dtype": dtype,
        "feature_dim": feature_dim,
        "images_per_sec": round(batch / dt, 1),
        "conv_tflops_per_chip": round(fl / dt / 1e12, 3),
        "seconds_per_batch": round(dt, 4),
    }


def measure_sift(batch: int, size: int, reps: int) -> dict:
    """On-chip dense SIFT (ops/sift_xla.py) img/s at the ImageNet geometry
    — the --sift-backend xla rate the north-star projection bounds."""
    import jax
    import jax.numpy as jnp

    from keystone_tpu.ops.sift_xla import dense_sift_xla

    rng = np.random.default_rng(0)
    imgs = jnp.asarray(
        rng.uniform(size=(batch, size, size)).astype(np.float32)
    )
    out = dense_sift_xla(imgs, step=4, bin_size=4)  # compile + warm-up
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = dense_sift_xla(imgs, step=4, bin_size=4)
        float(jnp.sum(out[0, 0]))  # force completion + tiny fetch
    dt = (time.perf_counter() - t0) / reps
    return {
        "kernel": "dense_sift_xla",
        "batch": batch,
        "size": size,
        "desc_per_img": int(out.shape[1]),
        "images_per_sec": round(batch / dt, 1),
        "seconds_per_batch": round(dt, 4),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--filters", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--sift-batch", type=int, default=64)
    ap.add_argument("--sift-size", type=int, default=256)
    ap.add_argument(
        "--dtypes", nargs="+", choices=["f32", "bf16"], default=["f32", "bf16"]
    )
    args = ap.parse_args()

    from keystone_tpu.utils.platform import ensure_live_backend

    backend = ensure_live_backend()
    rows = [
        measure(args.batch, args.filters, d, args.reps) for d in args.dtypes
    ]
    rows.append(measure_sift(args.sift_batch, args.sift_size, args.reps))
    print(
        json.dumps(
            {
                "metric": "cifar_featurize_images_per_sec",
                "backend": backend,
                "rows": rows,
            }
        )
    )


if __name__ == "__main__":
    main()
