"""Block-size / dtype MFU sweep for the BCD solver (BASELINE.md north-star
metric prep — VERDICT round-2 item 2).

For each (block, dtype) it runs the bench worker's solve, converts the
analytic FLOP count to TFLOPS/chip, and reports MFU against the chip's
plausible peak. Run on a live TPU:

    python tools/bench_mfu.py --blocks 1024 2048 4096 8192 --dtypes f32 bf16

On CPU it still runs (scaled-down problem, labelled) so the harness itself
stays verified while the chip is down. Prints one JSON line per config plus
a final summary table on stderr. Configs that clamp to the same effective
block (CPU scale has d=2048) are measured once.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # repo-root bench.py: worker protocol + plausible peaks


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--blocks", type=int, nargs="+",
                    default=[1024, 2048, 4096, 8192])
    ap.add_argument("--dtypes", nargs="+",
                    choices=sorted(bench.PLAUSIBLE_PEAK_TFLOPS),
                    default=["f32", "bf16", "f32h"])
    ap.add_argument("--timeout", type=float, default=900.0)
    ap.add_argument(
        "--scale",
        choices=["auto", "tpu", "tpu-xl"],
        default="auto",
        help="auto = tpu when live / cpu fallback; tpu-xl = the "
        "reference-scale d=262144 config (live TPU only)",
    )
    args = ap.parse_args()

    from keystone_tpu.utils.metrics import environment_fingerprint
    from keystone_tpu.utils.platform import cpu_mesh_env, probe_backend

    # One provenance line up front (deviceless: this process never inits
    # the backend — workers do); each row then only carries its backend.
    print(json.dumps({
        "metric": "env_fingerprint",
        **environment_fingerprint(devices=False),
    }), flush=True)

    def probe_live_tpu() -> bool:
        info = probe_backend(timeout=120)
        return info is not None and info.get("platform") != "cpu"

    live_tpu = probe_live_tpu()
    if args.scale == "auto":
        scale_key = "tpu" if live_tpu else "cpu"
    elif args.scale == "tpu-xl" and not live_tpu:
        print("tpu-xl scale needs a live TPU; falling back to cpu scale",
              file=sys.stderr)
        scale_key = "cpu"
    else:
        scale_key = args.scale
    base_env = dict(os.environ) if live_tpu else cpu_mesh_env(8)

    rows = []
    for dtype in args.dtypes:
        peak = bench.PLAUSIBLE_PEAK_TFLOPS[dtype]
        seen_blocks = set()
        for block in args.blocks:
            env = dict(base_env)
            env["KEYSTONE_BENCH_BLOCK"] = str(block)
            # KEYSTONE_PROFILE_DIR=... captures a jax profiler trace of
            # every sweep config: the worker's timed loop runs under
            # maybe_trace, and a per-config subdirectory keeps same-dtype
            # configs (identical worker-side tags) from overwriting each
            # other.
            if env.get("KEYSTONE_PROFILE_DIR"):
                env["KEYSTONE_PROFILE_DIR"] = os.path.join(
                    env["KEYSTONE_PROFILE_DIR"], f"mfu_b{block}_{dtype}"
                )
            # bench._run_worker tails worker stderr on failure — the
            # diagnostics contract the round-1 gate failure taught us.
            r = bench._run_worker(env, scale_key, dtype, args.timeout)
            if r is None or r.get("value") is None:
                print(json.dumps(
                    {"block": block, "dtype": dtype, "error": "run failed"}
                ))
                # A mid-sweep TPU death would otherwise cost one full
                # timeout per remaining config (tpu AND tpu-xl scales) —
                # re-probe and degrade.
                if scale_key != "cpu" and not probe_live_tpu():
                    print("TPU died mid-sweep; falling back to the CPU "
                          "scale for the rest", file=sys.stderr)
                    scale_key = "cpu"
                    base_env = cpu_mesh_env(8)
                continue
            actual_block = r["detail"]["block"]  # divisor-clamped by worker
            if actual_block in seen_blocks:
                continue
            seen_blocks.add(actual_block)
            mfu = r["value"] / peak
            line = {
                "block": actual_block,
                "dtype": dtype,
                "backend": r.get("backend"),
                "tflops_per_chip": r["value"],
                "mfu_vs_plausible_peak": round(mfu, 4),
                "seconds_per_solve": r["detail"]["seconds_per_solve"],
                # Accuracy rides with speed (the f32h-vs-f32 decision
                # needs both), matching the checkride sweep rows.
                "relative_residual": r["detail"].get("relative_residual"),
            }
            rows.append(line)
            print(json.dumps(line), flush=True)

    if rows:
        print("\nblock  dtype  backend  TFLOPS/chip   MFU", file=sys.stderr)
        for r in rows:
            print(
                f"{r['block']:>5}  {r['dtype']:<5}  {r['backend']:<7}"
                f"  {r['tflops_per_chip']:>10.3f}  {r['mfu_vs_plausible_peak']:>6.2%}",
                file=sys.stderr,
            )


if __name__ == "__main__":
    main()
