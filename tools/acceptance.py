"""Quality-floor acceptance harness (VERDICT r2 #5; SURVEY.md §7 stage-2
acceptance).

    python tools/acceptance.py <data-root> [--pipelines NAME ...]
    python tools/acceptance.py --synthetic [--pipelines NAME ...]

Runs every canonical pipeline against real datasets under <data-root> and
asserts the BASELINE.md floors, printing ONE pass/fail table and exiting
non-zero on any failure — so the first data-available session is a run,
not a porting exercise. `--synthetic` runs the deterministic generated
datasets with the CI floors instead (the same floors the test suite pins),
validating the harness itself in the no-network environment (synthetic
configs are the CI-scale ones the tests pin — full defaults are sized
for real data).

Expected <data-root> layout (every piece optional — missing data SKIPs):

    mnist/train.csv mnist/test.csv        (label-first CSV; or IDX pairs
                                           mnist/train-*, mnist/t10k-*)
    cifar/train.bin cifar/test.bin        (CIFAR-10 binary records)
    newsgroups/train/<group>/<doc>        (directory-per-class)
    newsgroups/test/<group>/<doc>
    amazon/train.jsonl amazon/test.jsonl  ({"reviewText", "overall"})
    timit/train.npz timit/test.npz        (features + labels arrays)
    voc/JPEGImages voc/Annotations        (train) + voc/Test{JPEGImages,
                                           Annotations}
    imagenet/train/<synset>.tar|/         + imagenet/val/... +
    imagenet/labels.txt                   (synset -> int label map)

Floors marked (provisional) come from BASELINE.md's low-confidence
reconstructed rows and must be re-derived when the reference mounts.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _mnist(root):
    from keystone_tpu.pipelines.images import mnist_random_fft as m

    if root is None:
        return m.run(m.MnistRandomFFTConfig(num_ffts=2, synthetic_n=1024))
    base = os.path.join(root, "mnist")
    csv_tr, csv_te = os.path.join(base, "train.csv"), os.path.join(base, "test.csv")
    if os.path.exists(csv_tr):
        tr, te = csv_tr, csv_te
    elif os.path.exists(os.path.join(base, "train-images-idx3-ubyte")):
        tr, te = os.path.join(base, "train"), os.path.join(base, "t10k")
    else:
        return None
    return m.run(m.MnistRandomFFTConfig(train_path=tr, test_path=te))


def _linear_pixels(root):
    from keystone_tpu.pipelines.images import linear_pixels as m

    if root is None:
        return m.run(m.LinearPixelsConfig(synthetic_n=1024))
    tr = os.path.join(root, "cifar", "train.bin")
    if not os.path.exists(tr):
        return None
    return m.run(
        m.LinearPixelsConfig(
            train_path=tr, test_path=os.path.join(root, "cifar", "test.bin")
        )
    )


def _cifar(root):
    from keystone_tpu.pipelines.images import random_patch_cifar as m

    if root is None:
        return m.run(
            m.RandomPatchCifarConfig(
                synthetic_n=768, num_filters=64, patch_sample=2000,
                num_iters=2, lam=5.0,
            )
        )
    tr = os.path.join(root, "cifar", "train.bin")
    if not os.path.exists(tr):
        return None
    return m.run(
        m.RandomPatchCifarConfig(
            train_path=tr, test_path=os.path.join(root, "cifar", "test.bin")
        )
    )


def _newsgroups(root):
    from keystone_tpu.pipelines.text import newsgroups as m

    if root is None:
        return m.run(m.NewsgroupsConfig(synthetic_n=600, num_features=500))
    tr = os.path.join(root, "newsgroups", "train")
    if not os.path.isdir(tr):
        return None
    return m.run(
        m.NewsgroupsConfig(
            train_path=tr, test_path=os.path.join(root, "newsgroups", "test")
        )
    )


def _amazon(root):
    from keystone_tpu.pipelines.text import amazon_reviews as m

    if root is None:
        return m.run(
            m.AmazonReviewsConfig(synthetic_n=600, num_features=500)
        )
    tr = os.path.join(root, "amazon", "train.jsonl")
    if not os.path.exists(tr):
        return None
    return m.run(
        m.AmazonReviewsConfig(
            train_path=tr, test_path=os.path.join(root, "amazon", "test.jsonl")
        )
    )


def _timit(root):
    from keystone_tpu.pipelines.speech import timit as m

    if root is None:
        return m.run(
            m.TimitConfig(
                synthetic_n=2048, num_features=1024, num_phones=12,
                num_iters=2, gamma=0.1,
            )
        )
    tr = os.path.join(root, "timit", "train.npz")
    if not os.path.exists(tr):
        return None
    return m.run(
        m.TimitConfig(
            features_path=tr,
            test_features_path=os.path.join(root, "timit", "test.npz"),
        )
    )


# Synthetic-run configs, shared by the runners AND the noise_band closed
# forms below (ADVICE r5: the band constants were independent hardcodes of
# these values — a drift in synthetic_classes/top_k would silently
# miscalibrate the band and pass out-of-band results).
VOC_SYNTH = dict(
    synthetic_n=96, synthetic_classes=4, pca_dims=24, gmm_k=4,
    descriptor_sample=20_000, num_iters=1,
)
IMAGENET_SYNTH = dict(
    synthetic_n=256, synthetic_classes=8, pca_dims=16, gmm_k=4,
    descriptor_sample=30_000, num_iters=1, top_k=5,
)


def _voc(root):
    from keystone_tpu.pipelines.images import voc_sift_fisher as m

    if root is None:
        return m.run(m.VOCSIFTFisherConfig(**VOC_SYNTH))
    img = os.path.join(root, "voc", "JPEGImages")
    if not os.path.isdir(img):
        return None
    return m.run(
        m.VOCSIFTFisherConfig(
            image_dir=img,
            annotation_dir=os.path.join(root, "voc", "Annotations"),
            test_image_dir=os.path.join(root, "voc", "TestJPEGImages"),
            test_annotation_dir=os.path.join(root, "voc", "TestAnnotations"),
        )
    )


def _imagenet(root):
    from keystone_tpu.pipelines.images import imagenet_sift_lcs_fv as m

    if root is None:
        return m.run(m.ImageNetSiftLcsFVConfig(**IMAGENET_SYNTH))
    tr = os.path.join(root, "imagenet", "train")
    if not os.path.isdir(tr):
        return None
    return m.run(
        m.ImageNetSiftLcsFVConfig(
            data_path=tr,
            test_data_path=os.path.join(root, "imagenet", "val"),
            label_map_path=os.path.join(root, "imagenet", "labels.txt"),
        )
    )


# name -> (runner, metric key, floor on real data, CI floor on synthetic,
#          higher_is_better, provenance)
# Real floors: BASELINE.md reference numbers (MNIST/CIFAR/TIMIT rows are
# low-confidence reconstructions — marked provisional). Synthetic floors:
# the test suite's pinned values (tests/test_*_pipeline*.py).
PIPELINES = {
    # CI floors assume the synthetic label-noise band (SYNTH_LABEL_NOISE
    # flips 10% of labels → even a perfect model scores ≈ 0.9 + 0.1/C on
    # accuracy metrics), so they sit BELOW the old separable-data values:
    # the run must land strictly between floor and ceiling to pass.
    "MnistRandomFFT": (_mnist, "test_accuracy", 0.96, 0.85, True, "BASELINE.md"),
    "LinearPixels": (_linear_pixels, "test_accuracy", 0.30, 0.50, True, "provisional"),
    "RandomPatchCifar": (_cifar, "test_accuracy", 0.80, 0.78, True, "BASELINE.md (84-85% full config)"),
    "NewsgroupsPipeline": (_newsgroups, "test_accuracy", 0.75, 0.80, True, "provisional"),
    # Amazon CI floor sits below the noisy-AUC ceiling (1-p = 0.90 at
    # p=0.1 — see noise_band) with a ≥0.10 window; 0.85 left only
    # [0.85, 0.90] and flaked (ADVICE r4).
    "AmazonReviewsPipeline": (_amazon, "auc", 0.85, 0.80, True, "provisional"),
    "TimitPipeline": (_timit, "phone_error_rate", 0.40, 0.20, False, "BASELINE.md (PER 33-34% full config)"),
    "VOCSIFTFisher": (_voc, "map", 0.45, 0.50, True, "provisional"),
    "ImageNetSiftLcsFV": (_imagenet, "top_k_error", 0.40, 0.60, False, "BASELINE.md (top-5 err 32-33% full config)"),
}

# Label-noise rate injected into the synthetic generators (overridable via
# a pre-set KEYSTONE_SYNTH_LABEL_NOISE). 0.1 puts every metric's
# best-possible value visibly below 1.0, making the floor/ceiling band
# meaningful.
SYNTH_LABEL_NOISE = 0.1


def noise_band(name: str, p: float):
    """Reachable-value band (lo, hi) for a pipeline's metric under the
    synthetic noise model (ADVICE r4: one accuracy-shaped band was
    miscalibrated for AUC / mAP / top-k error). ``None`` = unbounded side;
    the floor check already guards the other direction. Closed forms, all
    for a PERFECT model scored against noisy test labels:

    - accuracy — integer labels flip to a uniformly random OTHER class
      (synthetic.with_label_noise), so a flipped label never matches the
      true-class prediction: ceiling exactly 1-p, +p/2 realization slack.
    - AUC (balanced binary, flip rate p) — noisy-pos beats noisy-neg with
      prob (1-p)² + 2·½·p(1-p) = 1-p; ceiling 1-p, +p/4 slack.
    - multiclass error (PER) — perfect model errs on exactly the flipped
      fraction: floor p, ×½ slack.
    - top-k error (C classes) — a flipped label (uniform over C-1 others)
      still lands inside the model's remaining k-1 slots with prob
      (k-1)/(C-1): floor p·(C-k)/(C-1), ×½ slack.
    - mAP (per-ENTRY indicator flips, per-class prevalence π) — perfect
      ranking puts (1-p)·π·n kept positives on top (precision ≈ 1-p) and
      p·(1-π)·n flipped negatives uniform in the tail, where precision at
      depth t is ((1-p)π + p·t)/(π + t); integrating, the tail averages
      [p(1-π) + π(1-2p)·ln(1/π)]/(1-π). VOC synthetic prevalence is
      π = E[present classes]/C from the loader's own sampling rule.
      Ceiling + 0.05 slack (64-image test split is noisy).

    Every synthetic-run constant here (C, k, π) is read from VOC_SYNTH /
    IMAGENET_SYNTH / the VOC loader — the SAME objects the runners use —
    so the closed forms can't drift from the runs they bound (ADVICE r5).
    """
    import math

    from keystone_tpu.loaders.voc import VOCLoader

    acc_hi = 1.0 - p / 2.0
    def map_ceiling(pi):
        pos, neg = (1.0 - p) * pi, p * (1.0 - pi)
        tail = (p * (1.0 - pi) + pi * (1.0 - 2.0 * p) * math.log(1.0 / pi)) / (1.0 - pi)
        return (pos * (1.0 - p) + neg * tail) / (pos + neg)
    imagenet_c = IMAGENET_SYNTH["synthetic_classes"]
    imagenet_k = IMAGENET_SYNTH["top_k"]
    voc_pi = VOCLoader.SYNTH_PRESENT_CLASSES_MEAN / VOC_SYNTH["synthetic_classes"]
    bands = {
        "MnistRandomFFT": (None, acc_hi),
        "LinearPixels": (None, acc_hi),
        "RandomPatchCifar": (None, acc_hi),
        "NewsgroupsPipeline": (None, acc_hi),
        "AmazonReviewsPipeline": (None, (1.0 - p) + p / 4.0),
        "TimitPipeline": (p / 2.0, None),
        "ImageNetSiftLcsFV": (
            p * (imagenet_c - imagenet_k) / (imagenet_c - 1) / 2.0, None
        ),
        "VOCSIFTFisher": (None, map_ceiling(voc_pi) + 0.05),
    }
    return bands.get(name, (None, acc_hi if p < 0.5 else None))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("data_root", nargs="?", help="dataset root (see layout)")
    ap.add_argument("--synthetic", action="store_true",
                    help="run generated datasets with the CI floors")
    ap.add_argument("--pipelines", nargs="+", choices=sorted(PIPELINES),
                    help="subset to run (default: all)")
    ap.add_argument("--json", action="store_true",
                    help="also print one JSON line per pipeline")
    args = ap.parse_args(argv)
    if not args.synthetic and not args.data_root:
        ap.error("give a data root or --synthetic")
    root = None if args.synthetic else args.data_root

    # Honor the platform env BEFORE any pipeline touches jax: the axon
    # sitecustomize force-registers the TPU platform, and a dead chip
    # hangs backend init for minutes. The pipelines' own main()s do this
    # via setup_platform; run() called directly does not.
    from keystone_tpu.utils.platform import env_forces_cpu, force_cpu

    if env_forces_cpu():
        force_cpu()

    # Synthetic mode injects the known label-noise overlap so the floors
    # BIND (a 1.0 score now means the band check failed, not success); an
    # explicitly pre-set env value wins, and the default is restored after
    # the run so in-process callers (tests) don't leak noise into other
    # synthetic users.
    noise_preset = os.environ.get("KEYSTONE_SYNTH_LABEL_NOISE")
    if args.synthetic and noise_preset is None:
        os.environ["KEYSTONE_SYNTH_LABEL_NOISE"] = str(SYNTH_LABEL_NOISE)
    from keystone_tpu.loaders.synthetic import label_noise_rate

    noise = label_noise_rate() if args.synthetic else 0.0

    names = args.pipelines or list(PIPELINES)
    rows, failures = [], 0
    def emit(name, key, value, floor, status, dt, note):
        """One JSON line per pipeline for EVERY outcome — the checkride
        consumes these unattended, so ERROR rows must carry the message
        and every row must say which backend actually ran (a silent CPU
        fallback must never be read back as silicon evidence)."""
        if not args.json:
            return
        import jax

        print(json.dumps({"pipeline": name, "metric": key, "value": value,
                          "floor": floor, "status": status,
                          "ok": status == "PASS",
                          "backend": jax.default_backend(),
                          "note": note,
                          "seconds": round(dt, 1)}), flush=True)

    try:
        for name in names:
            runner, key, real_floor, ci_floor, higher, src = PIPELINES[name]
            floor = ci_floor if args.synthetic else real_floor
            t0 = time.time()
            try:
                out = runner(root)
            except Exception as e:  # a crash is a FAIL, not an abort
                err = f"{type(e).__name__}: {e}"
                dt = time.time() - t0
                rows.append((name, key, None, floor, "ERROR", dt, err))
                failures += 1
                emit(name, key, None, floor, "ERROR", dt, err)
                continue
            dt = time.time() - t0
            if out is None:
                rows.append((name, key, None, floor, "SKIP", dt, "no data"))
                emit(name, key, None, floor, "SKIP", dt, "no data")
                continue
            value = out.get(key)
            ok = value is not None and (
                value >= floor if higher else value <= floor
            )
            if ok and noise > 0.0:
                # The binding band: a score beyond the metric's noise-model
                # ceiling/floor (see noise_band) means the noise never
                # reached the metric — the harness is validating plumbing
                # again, not quality.
                lo, hi = noise_band(name, noise)
                band_ok = (lo is None or value >= lo) and (
                    hi is None or value <= hi
                )
                if not band_ok:
                    ok = False
                    bound = (f"> ceiling {hi:.4f}" if hi is not None
                             and value > hi else f"< floor {lo:.4f}")
                    src = (
                        f"OUT OF BAND (noise p={noise}, {bound}): metric "
                        "unreachable by a noisy-label run — floor not binding"
                    )
            status = "PASS" if ok else "FAIL"
            rows.append((name, key, value, floor, status, dt, src))
            if not ok:
                failures += 1
            emit(name, key, value, floor, status, dt, src)
    finally:
        if args.synthetic and noise_preset is None:
            del os.environ["KEYSTONE_SYNTH_LABEL_NOISE"]

    op = {True: ">=", False: "<="}
    print(f"\n{'pipeline':<22} {'metric':<18} {'value':>8} {'floor':>8}  verdict  {'sec':>7}  source")
    print("-" * 92)
    for name, key, value, floor, verdict, dt, src in rows:
        vs = "-" if value is None else f"{value:.4f}"
        sense = op[PIPELINES[name][4]]
        print(f"{name:<22} {key:<18} {vs:>8} {sense}{floor:<6.2f}  {verdict:<7} {dt:>6.1f}s  {src}")
    mode = "synthetic (CI floors)" if args.synthetic else f"real data at {root}"
    ran = sum(1 for r in rows if r[4] in ("PASS", "FAIL", "ERROR"))
    print(f"\n{mode}: {ran} ran, {failures} failed, "
          f"{sum(1 for r in rows if r[4] == 'SKIP')} skipped")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
