"""One-command, resumable live-chip evidence harness (`make tpu-checkride`).

Two rounds of TPU numbers have been lost to dead-chip sessions; the next
live window may be minutes long and unattended. This harness runs every
measurement the VERDICT asks for — bench f32, bench bf16, an MFU block
sweep, the Pallas FV Mosaic compile + parity-vs-XLA check, the streamed-BCD
H2D-overlap measurement, HBM memory stats, and the `entry()` compile —
checkpointing each step's JSON to a state dir the moment it finishes, so a
mid-ride relay death keeps every completed result. Re-running skips steps
that already succeeded ON TPU; steps whose stored result is a CPU fallback
are retried whenever the chip comes back. The aggregate is rewritten to
``TPU_REPORT.json`` after every step.

The chip-down path still runs everything on the forced 8-device CPU mesh
(each result tagged ``backend: "cpu"``) so the harness itself stays
verified while the chip is dead — the CPU dry-run is a harness test, not a
perf claim.

Per the relay's known fragility (a timeout-killed TPU job has taken the
tunnel down before), TPU liveness is re-probed between steps so a mid-ride
death degrades the REST of the ride to CPU instead of eating one full
timeout per remaining step.

Reference parity: this is the rebuild's analog of the reference's published
benchmark sweeps (SURVEY.md §6 / BASELINE.md north-star metric #2
[unverified — empty reference mount]).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # repo-root bench.py: worker protocol, scales, plausible peaks

# Ordered by evidence value per live-chip minute: one step of every CLASS
# before more rows of an already-captured class (a ~40 min window should
# yield maximal evidence diversity) — pallas_fv (never yet captured on
# silicon) right after the headline bench. bench_imagenet (the at-shape
# number the north star consumes — the r4 verdict's #2 priority) is a
# KNOWN relay hazard (~6.3 GiB residency, same class as bench_xl), so it
# runs only after EVERY cheap class has one row — but before the slow
# multi-row steps (acceptance, sweep), which resume/row-checkpoint and so
# lose least from a wedge after it. bench_xl stays LAST among
# measurements: its 2 GiB operands preceded two relay deaths (r3: the
# ride died on the first step after it).
STEPS = (
    "bench_f32",
    "pallas_fv",
    "roofline",
    "bench_bf16",
    "bench_trace",
    "streamed_overlap",
    "memory_stats",
    "featurize",
    "factor_primitives",
    "ring_vs_dp",
    "pipeline_rate",
    "bench_imagenet",
    "acceptance_synthetic",
    "mfu_sweep",
    "bench_xl",
    "entry_compile",
)

# Steps whose results describe the SOLVER's code path: a checkpoint from an
# older solver revision (bench.SOLVER_REV mismatch) is stale — re-measure
# on the next live window instead of skipping, and never report it as this
# round's number. Non-solver steps (pallas_fv, featurize, ...) keep their
# evidence across solver changes.
BENCH_FAMILY = frozenset(
    ("bench_f32", "bench_bf16", "bench_xl", "bench_imagenet", "mfu_sweep",
     "bench_trace")
)


# ---------------------------------------------------------------------------
# Orchestrator
# ---------------------------------------------------------------------------


def _state_path(state_dir: str, step: str) -> str:
    return os.path.join(state_dir, f"step_{step}.json")


def _load_state(state_dir: str, step: str):
    try:
        with open(_state_path(state_dir, step)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _save_state(state_dir: str, step: str, result: dict) -> None:
    os.makedirs(state_dir, exist_ok=True)
    # Wall-clock stamp INSIDE the record: the state dir is committed, and a
    # fresh checkout resets mtimes — bench.py's freshness guard must see
    # the measurement time, not the checkout time.
    result.setdefault("saved_at", time.time())
    tmp = _state_path(state_dir, step) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp, _state_path(state_dir, step))


def _write_report(state_dir: str, report_path: str, meta: dict) -> None:
    steps = {}
    for step in STEPS:
        r = _load_state(state_dir, step)
        if r is not None:
            steps[step] = r
    on_tpu = [
        s
        for s, r in steps.items()
        if r.get("backend") == "tpu"
        and r.get("ok")
        and not r.get("partial")
        and not r.get("quick_scale")
        and "error" not in r
        and not (s in BENCH_FAMILY and r.get("solver_rev") != bench.SOLVER_REV)
    ]
    report = {
        "meta": meta,
        "tpu_evidence_steps": on_tpu,
        "complete_on_tpu": sorted(on_tpu) == sorted(STEPS),
        "steps": steps,
    }
    # MFU against the chip's MEASURED gemm peak (the roofline step), not
    # the guessed PLAUSIBLE_PEAK constants — the honest denominator the
    # round-3 verdict asked for.
    # Provenance guard (ADVICE r4): a quick-mode roofline times tiny gemms
    # whose low "peak" would inflate MFU for full-scale bench rows — only
    # divide by a full-scale measured peak.
    roof = steps.get("roofline") or {}
    peaks = roof.get("measured_peak_tflops")
    if (peaks and roof.get("ok") and roof.get("backend") == "tpu"
            and roof.get("full_scale")):
        report["measured_peak_tflops"] = peaks
        for name in ("bench_f32", "bench_bf16", "bench_imagenet", "bench_xl"):
            r = steps.get(name)
            if r and r.get("tflops_per_chip") and r.get("backend") == "tpu":
                pk = peaks.get("bf16" if name.endswith("bf16") else "f32")
                if pk:
                    r["mfu_vs_measured_peak"] = round(
                        r["tflops_per_chip"] / pk, 4
                    )
    # Self-interpreting precision evidence: once the sweep has TPU rows
    # for both f32 (HIGHEST) and f32h (HIGH) at a shared block size, say
    # whether flipping config.solver_precision is supported — ≥1.3×
    # speedup at ≤2× residual — so a short window's output carries the
    # decision, not just the numbers.
    sweep = steps.get("mfu_sweep") or {}
    if (
        sweep.get("backend") == "tpu"
        # Same provenance gates as tpu_evidence_steps: retired-rev, toy
        # --quick, and mid-death partial sweeps must not drive a
        # production-default flip.
        and sweep.get("solver_rev") == bench.SOLVER_REV
        and not sweep.get("quick_scale")
        and not sweep.get("partial")
        and sweep.get("ok")
    ):
        by = {}
        for r in sweep.get("rows", []):
            if "error" not in r and r.get("tflops_per_chip"):
                by.setdefault(r["dtype"], {})[r["block"]] = r
        shared = sorted(
            set(by.get("f32", {})) & set(by.get("f32h", {})), reverse=True
        )
        if shared:
            blk = shared[0]  # largest shared block = the production regime
            a, h = by["f32"][blk], by["f32h"][blk]
            speedup = h["tflops_per_chip"] / a["tflops_per_chip"]
            ra, rh = a.get("relative_residual"), h.get("relative_residual")
            # No residual on either row = NO accuracy evidence: stay on
            # "highest" (the conservative default), never flip blind.
            resid_ok = ra is not None and rh is not None and rh <= 2.0 * ra
            report["precision_recommendation"] = {
                "block": blk,
                "f32_tflops": a["tflops_per_chip"],
                "f32h_tflops": h["tflops_per_chip"],
                "speedup": round(speedup, 2),
                "f32_residual": ra,
                "f32h_residual": rh,
                "recommend": (
                    "high" if speedup >= 1.3 and resid_ok else "highest"
                ),
                "reason": (
                    "missing residual evidence" if ra is None or rh is None
                    else f"speedup {speedup:.2f}x, residual "
                    f"{'ok' if resid_ok else 'degraded'}"
                ),
            }
    tmp = report_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1)
    os.replace(tmp, report_path)


def _probe(timeout: float) -> dict:
    from keystone_tpu.utils.platform import probe_backend

    t0 = time.time()
    info = probe_backend(timeout=timeout)
    wall = round(time.time() - t0, 1)
    if info is None:
        return {"live": False, "platform": None, "probe_seconds": wall}
    return {
        "live": info.get("platform") != "cpu",
        "platform": info.get("platform"),
        "n_devices": info.get("n"),
        "probe_seconds": wall,
    }


def _step_env(target: str, quick: bool) -> dict:
    from keystone_tpu.utils.platform import cpu_mesh_env

    if target == "tpu":
        env = dict(os.environ)
    else:
        env = cpu_mesh_env(8)
    if quick:
        env["KEYSTONE_CHECKRIDE_QUICK"] = "1"
    # Persistent XLA compile cache: a relay death after compile-but-before-
    # measure doesn't forfeit the (slow) first compile on the next attempt.
    # JAX reads this env var natively at import, so every child process —
    # step subprocesses AND bench workers — gets the cache without any
    # keystone setup call having to run first.
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".xla_compile_cache")
    )
    return env


def _bench_scale_for(target: str, quick: bool) -> str:
    if quick:
        return "quick"
    return "tpu" if target == "tpu" else "cpu"


def _forced_failure(step: str):
    """Test-only: KEYSTONE_CHECKRIDE_FAIL_STEP=<name> makes that step fail
    so the record-failure-and-continue path stays covered."""
    if os.environ.get("KEYSTONE_CHECKRIDE_FAIL_STEP") == step:
        return {"ok": False, "error": "forced_failure_for_test"}
    return None


def run_bench_step(step: str, target: str, quick: bool, timeout: float) -> dict:
    """bench_f32 / bench_bf16 measured via bench._run_worker directly from
    the orchestrator. The orchestrator process NEVER initializes a JAX
    backend, so the worker subprocess is the only process touching the chip
    — libtpu allows a single owner, and a backend-holding middleman would
    make every live-TPU bench fail with 'TPU already in use'."""
    dtype = "bf16" if step.endswith("bf16") else "f32"
    env = _step_env(target, quick)
    if step in ("bench_xl", "bench_imagenet"):
        # Full-scale-only rows: bench_xl is reference-scale d=262144
        # (SURVEY.md §6 TIMIT/CIFAR dims); bench_imagenet is the ImageNet
        # headline shape d=65536/k=1000 (SURVEY.md §2.11) whose at-shape
        # rate the north-star projection consumes directly. Only meaningful
        # on a live chip at full scale; --quick keeps the quick
        # harness-validation scale even on TPU (a multi-minute solve would
        # burn the short live window quick mode protects), and the
        # chip-down path skips outright — a CPU-degraded config would
        # duplicate bench_f32's evidence class without being either a
        # harness test or a perf claim.
        if target != "tpu":
            return {
                "ok": True,
                "backend": target,
                "skipped": "off-tpu: would duplicate bench_f32's config",
            }
        full_scale = {"bench_xl": "tpu-xl", "bench_imagenet": "tpu-imagenet"}
        scale = full_scale[step] if not quick else _bench_scale_for(target, quick)
    else:
        scale = _bench_scale_for(target, quick)
    r = bench._run_worker(env, scale, dtype, timeout)
    if r is None or r.get("value") is None:
        return {"ok": False, "backend": target, "error": "bench worker failed"}
    if r.get("suspect_timing"):
        # A reading above plausible peak is a transport lie. ok=False keeps
        # all three consumers honest at once: the resume check re-measures
        # instead of skipping, _write_report excludes it from TPU evidence,
        # and bench.py's replay guard never sees an ok checkpoint to serve.
        return {
            "ok": False,
            "backend": r.get("backend", target),
            "error": "suspect_timing: measured above plausible peak",
            "bench_line": r,
        }
    peak = bench.PLAUSIBLE_PEAK_TFLOPS[dtype]
    return {
        "ok": True,
        "backend": r.get("backend", target),
        "tflops_per_chip": r["value"],
        "mfu_vs_plausible_peak": round(r["value"] / peak, 4),
        "bench_line": r,
    }


def run_mfu_sweep(
    step: str, target: str, quick: bool, timeout: float, state_dir: str
) -> dict:
    """The block/dtype sweep, also orchestrator-side (same single-owner
    rule), checkpointing rows as they land: a mid-sweep death keeps every
    completed row, and a re-run resumes from the surviving rows."""
    scale = _bench_scale_for(target, quick)
    if scale == "quick":
        blocks = [64, 128]
    elif scale == "cpu":
        blocks = [512, 1024]
    else:
        blocks = [1024, 2048, 4096, 8192]

    prior = _load_state(state_dir, step) or {}
    if (
        target == "cpu"
        and prior.get("backend") == "tpu"
        and any("error" not in r for r in prior.get("rows", []))
    ):
        # Never overwrite checkpointed live-chip rows with a CPU-degraded
        # re-run — keeping partial TPU evidence is the point of the harness.
        return dict(prior, preserved_tpu_rows=True)
    # Resume only rows measured at this scale AND on this backend target —
    # in quick mode the scale is "quick" for both backends, and mixing
    # CPU-measured rows into a TPU-tagged result would fake evidence. Rows
    # from an older solver revision measured retired code: start fresh.
    rows = [
        r
        for r in prior.get("rows", [])
        if "error" not in r
        and prior.get("scale") == scale
        and prior.get("backend") == target
        and prior.get("solver_rev") == bench.SOLVER_REV
    ]
    done = {(r["dtype"], r["block"]) for r in rows}
    backend = prior.get("backend", target)
    # f32h (HIGH 3-pass precision) rows measure the candidate default
    # against "highest" — the flip decision is silicon-driven, not blind.
    for dtype in ("f32", "bf16", "f32h"):
        peak = bench.PLAUSIBLE_PEAK_TFLOPS[dtype]
        seen = {b for d, b in done if d == dtype}
        for block in blocks:
            env = _step_env(target, quick)
            env["KEYSTONE_BENCH_BLOCK"] = str(block)
            # A block that clamps to an already-measured effective block
            # would re-measure the same config; skip via the worker's
            # clamp rule (largest divisor of d that is <= block).
            # Cap each ROW well below the step timeout: a healthy row takes
            # <5 min, and the r3 ride burned 40 min of a dying relay's last
            # window on one wedged row before the death probe could fire.
            r = bench._run_worker(env, scale, dtype, min(timeout, 900.0))
            if r is not None and r.get("suspect_timing"):
                # Same transport-lie guard as run_bench_step: a row above
                # plausible peak must not be checkpointed as evidence (it
                # would win the "best" pick and be preserved forever).
                rows.append(
                    {"block": block, "dtype": dtype, "error": "suspect_timing"}
                )
                continue
            if r is None or r.get("value") is None:
                rows.append({"block": block, "dtype": dtype, "error": "failed"})
                # Mid-sweep death: re-probe once and stop burning timeouts.
                if target == "tpu" and not _probe(60)["live"]:
                    partial = {
                        "ok": bool(done),
                        "backend": backend,
                        "scale": scale,
                        "solver_rev": bench.SOLVER_REV,
                        "rows": rows,
                        "error": "tpu died mid-sweep",
                        # ok may be True (completed rows survive), so the
                        # orchestrator needs an explicit death signal to
                        # degrade the rest of the ride.
                        "tpu_dead": True,
                    }
                    _save_state(state_dir, step, dict(partial, step=step))
                    return partial
                continue
            actual = r["detail"]["block"]
            if actual in seen:
                continue
            seen.add(actual)
            done.add((dtype, actual))
            backend = r.get("backend", backend)
            rows.append(
                {
                    "block": actual,
                    "dtype": dtype,
                    "tflops_per_chip": r["value"],
                    "mfu_vs_plausible_peak": round(r["value"] / peak, 4),
                    "seconds_per_solve": r["detail"]["seconds_per_solve"],
                    # Accuracy evidence rides with the speed row: the
                    # f32h-vs-f32 default decision needs both.
                    "relative_residual": r["detail"].get("relative_residual"),
                }
            )
            # Checkpoint after EVERY row — the whole point of the harness.
            _save_state(
                state_dir,
                step,
                {
                    "ok": True,
                    "backend": backend,
                    "scale": scale,
                    "solver_rev": bench.SOLVER_REV,
                    "rows": rows,
                    "partial": True,
                    "step": step,
                },
            )
    ok_rows = [r for r in rows if "error" not in r]
    best = max(ok_rows, key=lambda r: r["tflops_per_chip"], default=None)
    result = {
        "ok": bool(ok_rows),
        "backend": backend,
        "scale": scale,
        "rows": rows,
        "best": best,
    }
    if len(ok_rows) < len(rows):
        # A row timed out on a LIVE chip (the per-row cap above exists to
        # trigger exactly this). Without this marker the step would be
        # finalized as done-on-TPU and the lost row never retried; the
        # resume filter already drops error rows, so a re-run retries them.
        result["partial"] = True
    return result


def run_acceptance_step(
    step: str, target: str, quick: bool, timeout: float
) -> dict:
    """All canonical pipelines end-to-end (`tools/acceptance.py --synthetic`)
    — on TPU this is the silicon wall-time + quality-floor evidence for the
    whole pipeline layer, not just the solver inner loop (SURVEY.md §2.11 /
    §7 stage-2 acceptance).

    Orchestrator-side like the bench steps: acceptance.py is the DIRECT
    child and the only process that initializes a backend (a backend-holding
    middleman would break the live-TPU single-owner rule), and a timeout
    kill reaches it rather than orphaning a grandchild on the chip."""
    env = _step_env(target, quick)
    cmd = [
        sys.executable,
        os.path.join(REPO, "tools", "acceptance.py"),
        "--synthetic",
        "--json",
    ]
    if quick:
        # Protect a minutes-long unattended window: two representative
        # pipelines (dense FFT front end + conv/solver vertical), not all.
        cmd += ["--pipelines", "MnistRandomFFT", "RandomPatchCifar"]
    proc, err = _run_child(cmd, env, timeout, target)
    if err is not None:
        return err
    rows = []
    for line in proc.stdout.splitlines():
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if isinstance(parsed, dict) and "pipeline" in parsed:
            rows.append(parsed)
    # The rows report the backend the child ACTUALLY ran on; a silent CPU
    # fallback under a TPU target must not be saved as TPU evidence (it
    # would flip complete_on_tpu on fake silicon numbers).
    seen = {r.get("backend") for r in rows if r.get("backend")}
    backend = seen.pop() if len(seen) == 1 else ("mixed" if seen else target)
    result = {
        "ok": proc.returncode == 0 and bool(rows) and backend == target,
        "backend": backend,
        "pipelines_passed": sum(1 for r in rows if r.get("ok")),
        "pipelines_total": len(rows),
        "rows": rows,
        "rc": proc.returncode,
    }
    if not result["ok"]:
        result["stderr_tail"] = (proc.stderr or "")[-1500:]
    return result


def _run_child(cmd: list, env: dict, timeout: float, target: str):
    """subprocess.run with the shared timeout/launch error contract: returns
    (proc, None) on launch success or (None, error_dict) otherwise — the one
    place to grow kill-grandchildren logic if the relay needs it."""
    try:
        proc = subprocess.run(
            cmd, env=env, capture_output=True, text=True, timeout=timeout
        )
    except subprocess.TimeoutExpired:
        return None, {"ok": False, "backend": target, "error": f"timeout>{timeout}s"}
    except OSError as e:
        return None, {"ok": False, "backend": target, "error": f"launch: {e}"}
    return proc, None


# Orchestrator-side tool steps: each runs a tools/ script as the DIRECT
# child (single backend owner, timeout reaches the real process) and trusts
# only the backend the script itself reports. Flags per (tpu, cpu/quick):
# CPU runs are harness validation, so they get scaled-down shapes.
TOOL_STEPS = {
    "featurize": (
        "bench_featurize.py",
        ["--filters", "1024", "--batch", "2048", "--reps", "3"],
        ["--filters", "64", "--batch", "128", "--reps", "2"],
    ),
    "factor_primitives": (
        "bench_factor.py",
        [],  # script defaults are the TPU sweep (blocks 1024..8192, n=32768)
        ["--blocks", "256", "512", "--n", "2048", "--k", "8"],
    ),
    # Single-chip caveat applies on TPU (the script records it): the ring's
    # comm advantage needs >1 chip, so the TPU row compares the two
    # programs' schedules at identical shapes; d is capped so the ring's
    # per-chip d_loc x d_loc gram fits HBM on one device.
    "ring_vs_dp": (
        "bench_ring.py",
        ["--n", "1024", "--k", "4", "--d-wide", "8192",
         "--d-control", "2048"],
        ["--n", "256", "--k", "4", "--d-wide", "4096",
         "--d-control", "1024", "--reps", "1"],
    ),
}


def run_tool_step(step: str, target: str, quick: bool, timeout: float) -> dict:
    script, tpu_flags, small_flags = TOOL_STEPS[step]
    flags = tpu_flags if target == "tpu" and not quick else small_flags
    env = _step_env(target, quick)
    cmd = [sys.executable, os.path.join(REPO, "tools", script)] + flags
    proc, err = _run_child(cmd, env, timeout, target)
    if err is not None:
        return err
    from keystone_tpu.utils.platform import parse_json_line

    parsed = parse_json_line(proc.stdout)
    if parsed is None or proc.returncode != 0:
        return {
            "ok": False,
            "backend": target,
            "error": f"rc={proc.returncode}, no JSON" if parsed is None
            else f"rc={proc.returncode}",
            "stderr_tail": (proc.stderr or "")[-1500:],
        }
    # The script probes and may fall back to CPU itself; never record that
    # fallback as TPU evidence.
    backend = parsed.get("backend", target)
    parsed["ok"] = backend == target
    parsed["backend"] = backend
    if not parsed["ok"]:
        parsed["error"] = f"ran on {backend}, target was {target}"
        parsed["stderr_tail"] = (proc.stderr or "")[-1500:]
    return parsed


def _run_step(step: str, target: str, quick: bool, timeout: float):
    """Run one step in a subprocess; return its parsed JSON dict or an
    error record. The subprocess boundary is what makes a hung backend
    survivable and the state file authoritative."""
    env = _step_env(target, quick)
    cmd = [sys.executable, os.path.abspath(__file__), "--step", step]
    t0 = time.time()
    proc, err = _run_child(cmd, env, timeout, target)
    if err is not None:
        return err
    from keystone_tpu.utils.platform import parse_json_line

    parsed = parse_json_line(proc.stdout)
    if parsed is None:
        return {
            "ok": False,
            "backend": target,
            "error": f"rc={proc.returncode}, no JSON",
            "stderr_tail": (proc.stderr or "")[-1500:],
        }
    parsed.setdefault("ok", True)
    parsed.setdefault("backend", target)
    parsed["seconds"] = round(time.time() - t0, 1)
    return parsed


def orchestrate(args) -> int:
    state_dir = args.state_dir
    probe = _probe(args.probe_timeout)
    target = "tpu" if probe["live"] else "cpu"
    meta = {
        "probe": probe,
        "started": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "quick": args.quick,
    }
    print(f"checkride: target={target} probe={probe}", file=sys.stderr)

    wanted = args.steps or list(STEPS)
    for step in wanted:
        prior = _load_state(state_dir, step)
        if prior is not None and not args.force:
            # A partial or error-carrying prior is never "done" — the sweep's
            # per-row checkpoints save ok=True mid-flight and must re-enter
            # the resume path, not get skipped.
            complete = (
                prior.get("ok")
                and not prior.get("partial")
                # Toy-scale (--quick) results validate the harness, not the
                # hardware: they satisfy another quick run but must never
                # block a full-scale re-measure.
                and (not prior.get("quick_scale") or args.quick)
                and "error" not in prior
                # A bench-family checkpoint from an older solver revision
                # measured code this round no longer ships — re-measure.
                and not (
                    step in BENCH_FAMILY
                    and prior.get("solver_rev") != bench.SOLVER_REV
                )
            )
            if complete and (prior.get("backend") == "tpu" or target == "cpu"):
                print(
                    f"checkride: skip {step} (done on {prior.get('backend')})",
                    file=sys.stderr,
                )
                continue
        print(f"checkride: run {step} on {target}", file=sys.stderr)
        forced = _forced_failure(step)
        if forced is not None:
            result = dict(forced, backend=target)
        elif step in ("bench_f32", "bench_bf16", "bench_xl", "bench_imagenet"):
            result = run_bench_step(step, target, args.quick, args.step_timeout)
        elif step == "mfu_sweep":
            result = run_mfu_sweep(
                step, target, args.quick, args.step_timeout, state_dir
            )
        elif step == "acceptance_synthetic":
            result = run_acceptance_step(
                step, target, args.quick, args.step_timeout
            )
        elif step in TOOL_STEPS:
            result = run_tool_step(step, target, args.quick, args.step_timeout)
        else:
            result = _run_step(step, target, args.quick, args.step_timeout)
        result["step"] = step
        if step in BENCH_FAMILY:
            # setdefault: a preserved prior (e.g. the sweep's CPU-rerun
            # guard returning checkpointed TPU rows) keeps the revision it
            # was MEASURED at — stamping it current would relabel old-rev
            # evidence as this solver's.
            result.setdefault("solver_rev", bench.SOLVER_REV)
        if args.quick:
            result["quick_scale"] = True
        _save_state(state_dir, step, result)
        _write_report(state_dir, args.report, meta)
        status = "ok" if result.get("ok") else f"FAIL ({result.get('error')})"
        print(f"checkride: {step}: {status} [{result.get('backend')}]", file=sys.stderr)
        # Mid-ride death check: if a TPU step failed, re-probe and degrade
        # the rest of the ride rather than timing out step after step.
        if target == "tpu" and (not result.get("ok") or result.get("tpu_dead")):
            # tpu_dead means the sweep itself just probed the chip dead —
            # don't burn another probe_timeout re-confirming it.
            if result.get("tpu_dead") or not _probe(args.probe_timeout)["live"]:
                print("checkride: TPU died mid-ride; degrading to CPU", file=sys.stderr)
                target = "cpu"
                meta["degraded_mid_ride"] = True

    _write_report(state_dir, args.report, meta)
    with open(args.report) as f:
        report = json.load(f)
    ok_steps = [s for s in wanted if report["steps"].get(s, {}).get("ok")]
    print(
        json.dumps(
            {
                "metric": "checkride_steps_ok",
                "value": len(ok_steps),
                "unit": f"of {len(STEPS)} steps",
                "complete_on_tpu": report["complete_on_tpu"],
                "report": args.report,
            }
        )
    )
    return 0 if len(ok_steps) == len(wanted) else 1


# ---------------------------------------------------------------------------
# Steps (each runs in its own subprocess and prints ONE JSON line)
# ---------------------------------------------------------------------------


def _quick() -> bool:
    return os.environ.get("KEYSTONE_CHECKRIDE_QUICK") == "1"


def _backend() -> str:
    from keystone_tpu.utils.platform import env_forces_cpu, force_cpu

    if env_forces_cpu():
        force_cpu()
    import jax

    return jax.default_backend()


def step_pallas_fv() -> dict:
    """Mosaic-compile the fused Fisher-vector kernel on TPU (interpret=True
    off-TPU — then this step only validates the harness path) and check
    parity + timing against the XLA backend."""
    backend = _backend()
    import numpy as np

    import jax
    import jax.numpy as jnp

    from keystone_tpu.ops.fisher_vector_pallas import fisher_vectors_pallas
    from keystone_tpu.nodes.images.external.fisher_vector import _fv_tpu

    rng = np.random.default_rng(0)
    if _quick() or backend != "tpu":
        bsz, m, d, k = 2, 256, 64, 16
    else:
        bsz, m, d, k = 8, 2048, 64, 256  # the ImageNet configuration
    X = rng.normal(size=(bsz, m, d)).astype(np.float32)
    w = np.abs(rng.normal(size=(k,))).astype(np.float32) + 0.1
    w /= w.sum()
    mu = rng.normal(size=(k, d)).astype(np.float32)
    var = np.abs(rng.normal(size=(k, d))).astype(np.float32) + 0.5

    interpret = backend != "tpu"
    t0 = time.perf_counter()
    out_p = fisher_vectors_pallas(
        jnp.asarray(X), jnp.asarray(w), jnp.asarray(mu), jnp.asarray(var),
        interpret=interpret,
    )
    jax.block_until_ready(out_p)
    compile_and_first = time.perf_counter() - t0
    out_x = _fv_tpu(jnp.asarray(X), jnp.asarray(w), jnp.asarray(mu), jnp.asarray(var))
    jax.block_until_ready(out_x)
    err = float(jnp.max(jnp.abs(out_p - out_x)))
    rel = err / max(float(jnp.max(jnp.abs(out_x))), 1e-30)

    def timed(fn, *a):
        reps, total = 0, 0.0
        while total < 1.0 and reps < 10:
            t = time.perf_counter()
            jax.block_until_ready(fn(*a))
            total += time.perf_counter() - t
            reps += 1
        return total / reps

    Xj = jnp.asarray(X)
    wj, muj, varj = jnp.asarray(w), jnp.asarray(mu), jnp.asarray(var)
    t_pallas = timed(
        lambda x: fisher_vectors_pallas(x, wj, muj, varj, interpret=interpret), Xj
    )
    t_xla = timed(lambda x: _fv_tpu(x, wj, muj, varj), Xj)
    return {
        "ok": rel < 1e-3,
        "backend": backend,
        "mosaic_compiled": not interpret,
        "max_rel_err_vs_xla": rel,
        "compile_plus_first_s": round(compile_and_first, 3),
        "pallas_s": round(t_pallas, 5),
        "xla_s": round(t_xla, 5),
        "speedup_vs_xla": round(t_xla / t_pallas, 3) if t_pallas else None,
        "config": {"batch": bsz, "m": m, "d": d, "k": k},
    }


def step_streamed_overlap() -> dict:
    """Measure what double-buffered H2D buys: the same streamed solve with
    and without prefetch overlap."""
    backend = _backend()
    import numpy as np

    from keystone_tpu.linalg import RowMatrix, block_coordinate_descent_streamed

    rng = np.random.default_rng(0)
    if _quick():
        n, d, k, block, iters = 512, 512, 4, 128, 2
    elif backend == "tpu":
        n, d, k, block, iters = 16384, 16384, 16, 2048, 2
    else:
        n, d, k, block, iters = 2048, 2048, 8, 512, 2
    A = rng.normal(size=(n, d)).astype(np.float32)
    B = RowMatrix.from_array(
        (A @ rng.normal(size=(d, k)).astype(np.float32)).astype(np.float32)
    )

    def run_once() -> float:
        t0 = time.perf_counter()
        W, _ = block_coordinate_descent_streamed(
            A, B, block_size=block, num_iters=iters, lam=1e-3
        )
        W[-1].block_until_ready()
        np.asarray(W[-1][-1, -1])
        return time.perf_counter() - t0

    run_once()  # warmup/compile
    overlapped = min(run_once() for _ in range(2))
    os.environ["KEYSTONE_STREAM_NO_OVERLAP"] = "1"
    try:
        run_once()  # recompile-free but re-warm the path
        serial = min(run_once() for _ in range(2))
    finally:
        del os.environ["KEYSTONE_STREAM_NO_OVERLAP"]
    # HBM high-water AFTER the timed loops (VERDICT r4 #4): the streamed
    # mode's whole claim is bounded residency — the number belongs in its
    # own evidence row. TPU runtimes report it; CPU records None.
    from keystone_tpu.utils.metrics import peak_hbm_bytes

    return {
        "ok": True,
        "backend": backend,
        "overlapped_s": round(overlapped, 4),
        "serial_s": round(serial, 4),
        "overlap_speedup": round(serial / overlapped, 3),
        "peak_hbm_bytes": peak_hbm_bytes(),
        "config": {"n": n, "d": d, "k": k, "block": block, "epochs": iters},
    }


def step_memory_stats() -> dict:
    """HBM high-water of the bench solve (memory_stats is TPU-only; CPU
    records availability=False so the step still validates)."""
    backend = _backend()
    import numpy as np

    import jax

    from keystone_tpu.linalg import RowMatrix, block_coordinate_descent

    p = bench.SCALE["quick" if _quick() else ("tpu" if backend == "tpu" else "cpu")]
    rng = np.random.default_rng(0)
    A = rng.normal(size=(p["n"], p["d"])).astype(np.float32)
    B = (A @ rng.normal(size=(p["d"], p["k"])).astype(np.float32)).astype(np.float32)
    Ma, Mb = RowMatrix.from_array(A), RowMatrix.from_array(B)
    W, _ = block_coordinate_descent(
        Ma, Mb, block_size=p["block"], num_iters=p["iters"], lam=1e-3,
        cache_grams=True,
    )
    W[-1].block_until_ready()
    dev = jax.local_devices()[0]
    stats = None
    try:
        stats = dev.memory_stats()
    except Exception:
        pass
    picked = None
    if stats:
        picked = {
            key: stats[key]
            for key in (
                "bytes_in_use",
                "peak_bytes_in_use",
                "bytes_limit",
                "largest_alloc_size",
            )
            if key in stats
        }
    return {
        "ok": True,
        "backend": backend,
        "memory_stats_available": bool(stats),
        "memory": picked,
        "config": p,
    }


def step_roofline() -> dict:
    """Measured-peak roofline on the solver's own op shapes: pure gemms
    (gram-shaped and square, f32-HIGHEST and bf16) plus the factorization
    primitives at the solver block size. The gemm peaks become the MFU
    denominators for every bench row (vs the guessed PLAUSIBLE_PEAK
    constants), and the factor rates bound how much of the solve can ever
    be MXU-bound. Ref: SURVEY.md §6 north-star metric #2."""
    backend = _backend()
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax import lax

    import bench

    full = backend == "tpu" and not _quick()
    # Gram shape matches the bench solve's dominant gemm; square is the
    # MXU-friendliest shape the chip will ever see (the true ceiling).
    n, b, sq = (32768, 4096, 8192) if full else (2048, 256, 512)

    def timed(fn, *args):
        jax.block_until_ready(fn(*args))  # compile + warm
        reps, total = 0, 0.0
        while total < 1.0 and reps < 20:
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            total += time.perf_counter() - t0
            reps += 1
        return total / reps

    rng = np.random.default_rng(0)
    rows, peaks = {}, {}
    for key, dtype in (
        ("f32", jnp.float32),
        ("bf16", jnp.bfloat16),
        ("f32h", jnp.float32),  # HIGH 3-pass: the candidate solver default
    ):
        prec = {
            "f32": lax.Precision.HIGHEST,
            "f32h": lax.Precision.HIGH,
            "bf16": lax.Precision.DEFAULT,
        }[key]

        @jax.jit
        def mm(x, y, _p=prec):
            return jnp.matmul(
                x, y, precision=_p, preferred_element_type=jnp.float32
            )

        x = jnp.asarray(rng.normal(size=(b, n)), dtype=dtype)
        y = jnp.asarray(rng.normal(size=(n, b)), dtype=dtype)
        dt = timed(mm, x, y)
        gram_tf = 2.0 * b * b * n / dt / 1e12
        rows[f"gram_gemm_{key}"] = {
            "shape": [b, n, b], "seconds": round(dt, 5),
            "tflops": round(gram_tf, 2),
        }
        xs = jnp.asarray(rng.normal(size=(sq, sq)), dtype=dtype)
        ys = jnp.asarray(rng.normal(size=(sq, sq)), dtype=dtype)
        dts = timed(mm, xs, ys)
        sq_tf = 2.0 * sq**3 / dts / 1e12
        rows[f"square_gemm_{key}"] = {
            "shape": [sq, sq, sq], "seconds": round(dts, 5),
            "tflops": round(sq_tf, 2),
        }
        peaks[key] = round(max(gram_tf, sq_tf), 2)

    # Factorization primitives at the solver block size (f32, like the
    # solver's accum dtype): single vs batch-8 SPD inverse — the measured
    # basis for the _factor_chunk batching policy.
    from keystone_tpu.linalg.bcd import _batched_spd_inv

    xg = jnp.asarray(rng.normal(size=(b, b)), dtype=jnp.float32)
    g = (xg @ xg.T) / b + jnp.eye(b, dtype=jnp.float32)
    inv_flops = b**3 / 3.0 + 2.0 * b**3
    binv = jax.jit(_batched_spd_inv)
    dt1 = timed(binv, g[None])
    g8 = jnp.repeat(g[None], 8, axis=0)
    dt8 = timed(binv, g8)
    rows["spd_inverse_single"] = {
        "b": b, "seconds": round(dt1, 5),
        "tflops": round(inv_flops / dt1 / 1e12, 2),
    }
    rows["spd_inverse_batch8"] = {
        "b": b, "seconds": round(dt8, 5),
        "tflops": round(8 * inv_flops / dt8 / 1e12, 2),
        "speedup_vs_8_singles": round(8 * dt1 / dt8, 2),
    }

    suspect = any(
        peaks[key] > bench.PLAUSIBLE_PEAK_TFLOPS[key] * 1.1 for key in peaks
    )
    out = {
        "ok": not suspect,
        "backend": backend,
        "full_scale": full,
        "measured_peak_tflops": peaks,
        "rows": rows,
    }
    if suspect:
        out["error"] = "suspect_timing: gemm measured above plausible peak"
    return out


def step_bench_trace() -> dict:
    """Phase-decomposed fused bench solve + an xprof trace artifact.

    Answers the round-3 verdict's #1 open question — where do the other
    ~90% of peak go? — by timing the solve's three programs (stack /
    factor / epochs) and the result fetch separately, each with its own
    FLOP count, then capturing a jax.profiler trace of one full solve for
    offline op-level attribution."""
    backend = _backend()
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import bench
    from keystone_tpu.config import config
    from keystone_tpu.linalg import RowMatrix, bcd, block_coordinate_descent
    from keystone_tpu.linalg.row_matrix import _precision

    p = bench.SCALE["quick" if _quick() else ("tpu" if backend == "tpu" else "cpu")]
    n, d, k, block, iters = p["n"], p["d"], p["k"], p["block"], p["iters"]
    rng = np.random.default_rng(0)
    A = rng.normal(size=(n, d)).astype(np.float32)
    B = (A @ rng.normal(size=(d, k)).astype(np.float32)).astype(np.float32)
    Ma, Mb = RowMatrix.from_array(A), RowMatrix.from_array(B)
    mesh, axis = Ma.mesh, config.data_axis
    precision = _precision()
    nb = d // block
    lam = jnp.asarray(1e-3, jnp.float32)
    w_rows = jax.device_put(
        jnp.zeros((Ma.padded_rows,), jnp.float32),
        NamedSharding(mesh, P(axis)),
    )

    def timed(fn, reps=3):
        fn()  # compile + warm
        total = 0.0
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            total += time.perf_counter() - t0
        return total / reps

    stack_fn = bcd._stack_blocks_fn(mesh, axis, nb)
    a3 = stack_fn(Ma.data)
    stack_s = timed(lambda: jax.block_until_ready(stack_fn(Ma.data)))

    factor_fn = bcd._fused_factor_fn(mesh, axis, precision, False)
    invs = factor_fn(a3, lam, w_rows)
    factor_s = timed(
        lambda: jax.block_until_ready(factor_fn(a3, lam, w_rows))
    )
    factor_flops = nb * (2.0 * n * block**2 + block**3 / 3.0 + 2.0 * block**3)

    ep_fn = bcd._fused_epochs_fn(mesh, axis, precision, False, iters, True)

    def run_epochs():
        # The epochs program DONATES residual and weights — rebuild fresh
        # carries per rep (outside would hide the donation's benefit;
        # inside costs two small allocs, consistent across reps).
        R = jnp.array(Mb.data, dtype=jnp.float32)
        W3 = jnp.zeros((nb, block, k), dtype=jnp.float32)
        R, W3 = ep_fn(a3, invs, R, W3, lam, w_rows)
        jax.block_until_ready(W3)
        return W3

    epochs_s = timed(run_epochs)
    epoch_flops = nb * iters * (6.0 * n * block * k + 2.0 * block * block * k)
    W3 = run_epochs()
    fetch_s = timed(lambda: np.asarray(W3[-1][-1, -1]))

    # End-to-end through the public API (same path the bench times): the
    # gap between this and the phase sum is dispatch/host overhead.
    def run_public():
        W, _ = block_coordinate_descent(
            Ma, Mb, block_size=block, num_iters=iters, lam=1e-3,
            cache_grams=True,
        )
        np.asarray(W[-1][-1, -1])

    e2e_s = timed(run_public)

    trace_info = None
    if backend == "tpu":
        trace_dir = os.path.join(REPO, ".checkride", "xprof")
        os.makedirs(trace_dir, exist_ok=True)
        with jax.profiler.trace(trace_dir):
            run_public()
        n_files, n_bytes = 0, 0
        for root, _dirs, files in os.walk(trace_dir):
            for fname in files:
                n_files += 1
                n_bytes += os.path.getsize(os.path.join(root, fname))
        trace_info = {"dir": trace_dir, "files": n_files, "bytes": n_bytes}

    phase_sum = stack_s + factor_s + epochs_s + fetch_s
    return {
        "ok": True,
        "backend": backend,
        "config": {"n": n, "d": d, "k": k, "block": block, "epochs": iters},
        "phases": {
            "stack": {"seconds": round(stack_s, 4)},
            "factor": {
                "seconds": round(factor_s, 4),
                "tflops": round(factor_flops / factor_s / 1e12, 2),
            },
            "epochs": {
                "seconds": round(epochs_s, 4),
                "tflops": round(epoch_flops / epochs_s / 1e12, 2),
            },
            "fetch": {"seconds": round(fetch_s, 4)},
        },
        "phase_sum_s": round(phase_sum, 4),
        "end_to_end_s": round(e2e_s, 4),
        "dispatch_overhead_s": round(e2e_s - phase_sum, 4),
        "xprof_trace": trace_info,
    }


def step_pipeline_rate() -> dict:
    """End-to-end single-chip pipeline rate at the FULL per-image geometry.

    The north-star projection previously summed per-stage models with no
    measured end-to-end anchor (VERDICT r3 missing #6). This step runs the
    ImageNetSiftLcsFV featurize→FV→solve program on synthetic 256px images
    at the reference per-image config (step 4, pca 64, gmm_k 256, on-chip
    SIFT, device FV) and reports img/s plus per-stage seconds — the
    measured anchor tools/northstar.py consumes directly."""
    backend = _backend()
    import numpy as np

    from keystone_tpu.loaders.imagenet import ImageNetLoader
    from keystone_tpu.nodes.learning import BlockWeightedLeastSquaresEstimator
    from keystone_tpu.nodes.util import ClassLabelIndicators
    from keystone_tpu.pipelines.images.imagenet_sift_lcs_fv import (
        ImageNetSiftLcsFVConfig,
        build_featurizer,
    )

    if _quick() or backend != "tpu":
        # Harness validation: tiny geometry, CI-scale featurizer.
        n, size, gmm_k, pca, batch, sample = 48, 64, 4, 16, 16, 20_000
        epochs = 1
    else:
        n, size, gmm_k, pca, batch, sample = 2048, 256, 256, 64, 128, 200_000
        epochs = 3
    classes = 16
    conf = ImageNetSiftLcsFVConfig(
        gmm_k=gmm_k,
        pca_dims=pca,
        sift_backend="xla",
        fv_backend="tpu",
        descriptor_sample=sample,
        synthetic_n=n,
        synthetic_classes=classes,
    )
    train, _test = ImageNetLoader.synthetic(
        n=n, num_classes=classes, size=size
    )

    t0 = time.perf_counter()
    featurizer = build_featurizer(conf, train.data[: min(n, 512)])
    fit_s = time.perf_counter() - t0

    # Warm one batch (compile), then time the featurize stream.
    _ = np.asarray(featurizer(train.data[:batch]).get())
    t0 = time.perf_counter()
    feats = []
    for s in range(0, n, batch):
        feats.append(np.asarray(featurizer(train.data[s : s + batch]).get()))
    featurize_s = time.perf_counter() - t0
    A = np.concatenate(feats, axis=0)
    del feats
    feature_dim = A.shape[1]

    targets = np.asarray(ClassLabelIndicators(classes)(train.labels))
    solver = BlockWeightedLeastSquaresEstimator(
        num_iters=epochs, lam=conf.lam, mixture_weight=conf.mixture_weight
    )
    t0 = time.perf_counter()
    model = solver.fit(A, targets)
    # Force a device→host fetch so async dispatch can't end the timer early.
    np.asarray(model.W_blocks[-1][-1, -1])
    solve_s = time.perf_counter() - t0

    total_s = fit_s + featurize_s + solve_s
    return {
        "ok": True,
        "backend": backend,
        "config": {
            "images": n, "size_px": size, "gmm_k": gmm_k, "pca_dims": pca,
            "feature_dim": feature_dim, "classes": classes,
            "solver_epochs": epochs, "sift_backend": "xla",
        },
        "featurize_img_per_sec": round(n / featurize_s, 2),
        "stages_s": {
            "fit_pca_gmm": round(fit_s, 2),
            "featurize": round(featurize_s, 2),
            "solve": round(solve_s, 2),
        },
        "end_to_end_s": round(total_s, 2),
        "end_to_end_img_per_sec": round(n / total_s, 2),
    }


def step_entry_compile() -> dict:
    import jax

    import __graft_entry__

    t0 = time.perf_counter()
    fn, args = __graft_entry__.entry()
    build_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    return {
        "ok": True,
        "backend": jax.default_backend(),
        "build_s": round(build_s, 2),
        "compile_plus_first_s": round(compile_s, 2),
        "out_shape": list(out.shape),
    }


STEP_FNS = {
    "pallas_fv": step_pallas_fv,
    "roofline": step_roofline,
    "bench_trace": step_bench_trace,
    "streamed_overlap": step_streamed_overlap,
    "memory_stats": step_memory_stats,
    "pipeline_rate": step_pipeline_rate,
    "entry_compile": step_entry_compile,
}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--step", choices=list(STEP_FNS), default=None)
    ap.add_argument("--steps", nargs="+", choices=list(STEPS), default=None)
    ap.add_argument("--state-dir", default=os.path.join(REPO, ".checkride"))
    ap.add_argument("--report", default=os.path.join(REPO, "TPU_REPORT.json"))
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--force", action="store_true")
    ap.add_argument("--probe-timeout", type=float, default=120.0)
    # Generous per-step budget: a cold TPU compile through the relay can be
    # slow, and killing live TPU work has taken the relay down before.
    ap.add_argument("--step-timeout", type=float, default=2400.0)
    args = ap.parse_args()

    if args.step:
        result = STEP_FNS[args.step]()
        print(json.dumps(result), flush=True)
        sys.exit(0 if result.get("ok") else 1)
    sys.exit(orchestrate(args))


if __name__ == "__main__":
    main()
