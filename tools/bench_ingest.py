"""ImageNet ingest throughput benchmark (SURVEY.md §7 hard part 4).

Generates a synthetic-JPEG synset tree, then measures:
1. decode -> NHWC rate (images/sec) of the PIL thread pool at 256px,
   swept over worker counts;
2. the featurization rate of a representative conv patch-extraction step
   on the default backend;
3. overlapped streaming (decode-ahead batches feeding featurization)
   vs serial decode-then-featurize.

Usage: python tools/bench_ingest.py [--images 512] [--size 256]
Prints one JSON line; paste the numbers into NOTES_r2.md.

--stream-solve switches to the chunked-solver overlap benchmark instead:
a synthetic out-of-core row stream whose producer is priced like a real
fixture read (simulated storage latency + zlib deserialize, both
GIL-releasing — the work a prefetch thread CAN overlap with compute)
feeds ``solve_least_squares_chunked`` serialized, synchronously
(prefetch_depth=0), and overlapped (the PrefetchIterator +
double-buffered H2D + donated accumulation path), and the line reports
the overlap ratios plus queue-depth-bounded peak-residency evidence
(``utils.metrics.peak_hbm_bytes`` where the runtime exposes it; the
host-side depth×batch bound always).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_jpeg_tree(root: str, n_images: int, size: int, synsets: int = 8) -> dict:
    """Class-textured JPEGs in <synset>/ dirs; returns the label map."""
    from PIL import Image

    rng = np.random.default_rng(0)
    label_map = {}
    base, extra = divmod(n_images, synsets)
    for s in range(synsets):
        name = f"n{s:08d}"
        label_map[name] = s
        d = os.path.join(root, name)
        os.makedirs(d, exist_ok=True)
        per = base + (1 if s < extra else 0)  # remainder distributed
        for i in range(per):
            x = rng.uniform(size=(size, size, 3))
            yy, xx = np.mgrid[0:size, 0:size]
            x[..., 0] = 0.5 + 0.5 * np.sin(2 * np.pi * (s + 2) / size * xx)
            img = Image.fromarray((x * 255).astype(np.uint8))
            img.save(os.path.join(d, f"img_{i:05d}.JPEG"), quality=90)
    return label_map


def stream_solve(args) -> None:
    """Synchronous vs overlapped out-of-core normal-equations ingest.

    The producer is a synthetic fixture READ priced like real out-of-core
    ingest: a simulated storage/network latency (``--io-ms``) plus a real
    zlib decompress + deserialize of the chunk — both release the GIL, as
    real file/socket I/O and codec work do, which is exactly the work a
    prefetch thread can overlap with compute. Three modes are timed
    (best-of ``--reps`` each, pipelines are latency-noisy on shared
    hosts):

    - serialized: prefetch_depth=0 under KEYSTONE_STREAM_NO_OVERLAP=1 —
      ingest and compute strictly alternate (the true no-overlap cost);
    - async-dispatch: prefetch_depth=0 as it ships — one thread, but
      XLA's async dispatch already pipelines compute under host work;
    - overlapped: the PrefetchIterator + double-buffered H2D + donated
      accumulation path.
    """
    from keystone_tpu.utils.platform import ensure_live_backend

    backend = ensure_live_backend()
    import jax

    from keystone_tpu.linalg import solve_least_squares_chunked
    from keystone_tpu.loaders.stream import PrefetchIterator
    from keystone_tpu.utils.metrics import (
        environment_fingerprint,
        maybe_trace,
        peak_hbm_bytes,
    )

    import zlib

    rows, d, k, chunks = args.chunk_rows, args.d, args.k, args.chunks
    depth, io_s = args.depth, args.io_ms / 1e3
    rng = np.random.default_rng(0)
    W_true = rng.normal(size=(d, k)).astype(np.float32)
    X0 = (rng.normal(size=(rows, d)) / np.sqrt(d)).astype(np.float32)
    Y0 = X0 @ W_true
    xblob = zlib.compress(X0.tobytes(), args.zlevel)
    yblob = zlib.compress(Y0.tobytes(), args.zlevel)

    def stream():
        for _ in range(chunks):
            time.sleep(io_s)  # storage/network latency stand-in
            X = np.frombuffer(zlib.decompress(xblob), dtype=np.float32)
            Y = np.frombuffer(zlib.decompress(yblob), dtype=np.float32)
            yield X.reshape(rows, d), Y.reshape(rows, k)

    def run_once(run_depth, serialize=False):
        # Pin the serialize knob BOTH ways: an inherited
        # KEYSTONE_STREAM_NO_OVERLAP=1 would otherwise silently turn the
        # async/overlapped reps into serialized ones.
        prior = os.environ.get("KEYSTONE_STREAM_NO_OVERLAP")
        if serialize:
            os.environ["KEYSTONE_STREAM_NO_OVERLAP"] = "1"
        else:
            os.environ.pop("KEYSTONE_STREAM_NO_OVERLAP", None)
        pf = None
        try:
            t0 = time.perf_counter()
            if run_depth > 0:
                pf = PrefetchIterator(stream(), run_depth)
                W = solve_least_squares_chunked(pf, lam=1e-3)
            else:
                W = solve_least_squares_chunked(
                    stream(), lam=1e-3, prefetch_depth=0
                )
            jax.block_until_ready(W)
            return time.perf_counter() - t0, pf
        finally:
            if prior is None:
                os.environ.pop("KEYSTONE_STREAM_NO_OVERLAP", None)
            else:
                os.environ["KEYSTONE_STREAM_NO_OVERLAP"] = prior

    # Producer-only cost, for the producer≈consumer context of the ratio.
    t0 = time.perf_counter()
    for _ in stream():
        pass
    producer_s = time.perf_counter() - t0

    run_once(0)  # warm both paths' compile caches
    run_once(depth)
    reps = max(1, args.reps)
    # KEYSTONE_PROFILE_DIR=... captures a jax profiler trace of the timed
    # reps (all three modes), no code edits needed.
    with maybe_trace("bench_ingest_stream_solve"):
        serial_s = min(run_once(0, serialize=True)[0] for _ in range(reps))
        async_s = min(run_once(0)[0] for _ in range(reps))
        timed = [run_once(depth) for _ in range(reps)]
    overlap_s, pf = min(timed, key=lambda t: t[0])

    chunk_bytes = rows * (d + k) * 4
    print(json.dumps({
        "metric": "stream_solve_overlap",
        "backend": backend,
        "host_cores": os.cpu_count(),
        "env": environment_fingerprint(),
        "chunks": chunks, "chunk_rows": rows, "d": d, "k": k,
        "io_ms": args.io_ms, "reps": reps,
        "producer_only_seconds": round(producer_s, 3),
        "sync_seconds": round(serial_s, 3),
        "async_dispatch_seconds": round(async_s, 3),
        "overlapped_seconds": round(overlap_s, 3),
        "overlap_ratio": round(serial_s / overlap_s, 3),
        "overlap_vs_async_ratio": round(async_s / overlap_s, 3),
        # Residency evidence: the queue can never hold more than depth
        # batches (max_queued is the observed high-water), so host
        # residency above the synchronous path is bounded by depth × chunk
        # bytes; on runtimes that report it, peak_hbm_bytes shows the
        # device side staying at two in-flight chunk buffers (donated
        # accumulation).
        "queue_depth": depth,
        "max_queued_batches": pf.max_queued if pf is not None else None,
        "host_residency_bound_bytes": depth * chunk_bytes,
        "chunk_bytes": chunk_bytes,
        "peak_hbm_bytes": peak_hbm_bytes(),
    }))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=512)
    ap.add_argument("--size", type=int, default=256)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--workers", type=int, nargs="+", default=[4, 8, 16, 32])
    ap.add_argument("--stream-solve", action="store_true",
                    help="benchmark sync vs overlapped chunked solve "
                    "ingestion instead of the JPEG decode sweep")
    ap.add_argument("--chunks", type=int, default=16,
                    help="[stream-solve] chunks in the synthetic stream")
    ap.add_argument("--chunk-rows", type=int, default=2048,
                    help="[stream-solve] rows per chunk")
    ap.add_argument("--d", type=int, default=1024,
                    help="[stream-solve] feature dimension (defaults chosen "
                    "so producer cost ≈ consumer cost per chunk)")
    ap.add_argument("--k", type=int, default=8,
                    help="[stream-solve] target columns")
    ap.add_argument("--depth", type=int, default=2,
                    help="[stream-solve] prefetch queue depth")
    ap.add_argument("--io-ms", type=float, default=50.0,
                    help="[stream-solve] simulated storage latency per chunk")
    ap.add_argument("--zlevel", type=int, default=0,
                    help="[stream-solve] fixture compression level (0 = "
                    "stored blocks: pure deserialize, latency-dominated "
                    "producer — the stable default; raise it to price a "
                    "codec-heavy producer)")
    ap.add_argument("--reps", type=int, default=4,
                    help="[stream-solve] timing repetitions (best-of)")
    args = ap.parse_args()

    if args.stream_solve:
        stream_solve(args)
        return

    from keystone_tpu.utils.platform import ensure_live_backend

    backend = ensure_live_backend()
    import jax
    import jax.numpy as jnp
    from jax import lax

    from keystone_tpu.loaders.imagenet import ImageNetLoader
    from keystone_tpu.utils.metrics import environment_fingerprint, maybe_trace

    # The loader caps pool size at the core count (decode is CPU-bound;
    # NOTES_r2 §8's non-monotone sweep was oversubscription thrash on a
    # 1-core host), so requested counts above nproc clamp — the table
    # records the EFFECTIVE pool size.
    result: dict = {
        "metric": "imagenet_ingest",
        "backend": backend,
        "host_cores": os.cpu_count(),
        "env": environment_fingerprint(),
    }
    with tempfile.TemporaryDirectory() as root:
        label_map = make_jpeg_tree(root, args.images, args.size)

        # 1. raw decode rate: PIL thread pool per worker count vs the
        # native libjpeg/OpenMP pool. Save/restore any user override.
        decode = {}
        prior = os.environ.get("KEYSTONE_JPEG_BACKEND")
        try:
            os.environ["KEYSTONE_JPEG_BACKEND"] = "pil"
            from keystone_tpu.loaders.imagenet import _pool_workers

            for w in args.workers:
                eff = _pool_workers(w)
                key = f"pil-{eff}"
                if key in decode:
                    continue  # clamped to an already-measured pool size
                t0 = time.perf_counter()
                data = ImageNetLoader.load(
                    root, label_map, size=args.size, workers=w
                )
                dt = time.perf_counter() - t0
                decode[key] = round(len(data.data) / dt, 1)
            from keystone_tpu import native

            if native.jpeg_available():
                os.environ["KEYSTONE_JPEG_BACKEND"] = "native"
                t0 = time.perf_counter()
                data = ImageNetLoader.load(root, label_map, size=args.size)
                decode["native"] = round(
                    len(data.data) / (time.perf_counter() - t0), 1
                )
        finally:
            if prior is None:
                os.environ.pop("KEYSTONE_JPEG_BACKEND", None)
            else:
                os.environ["KEYSTONE_JPEG_BACKEND"] = prior
        result["decode_images_per_sec"] = decode
        best_rate = max(decode.values())

        # 2. featurization rate: conv patch extraction + pool, the front of
        # the RandomPatchCifar/ImageNet featurization stack.
        filters = jnp.asarray(
            np.random.default_rng(0).normal(size=(6, 6, 3, 64)) * 0.1,
            dtype=jnp.float32,
        )

        @jax.jit
        def featurize(X):
            out = lax.conv_general_dilated(
                X, filters, (2, 2), "VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
            return jnp.maximum(out, 0.0).mean(axis=(1, 2))

        X0 = jnp.asarray(data.data[: args.batch])
        jax.block_until_ready(featurize(X0))  # compile
        t0 = time.perf_counter()
        reps = 5
        for _ in range(reps):
            jax.block_until_ready(featurize(X0))
        feat_rate = args.batch * reps / (time.perf_counter() - t0)
        result["featurize_images_per_sec"] = round(feat_rate, 1)
        result["decode_feeds_featurization"] = best_rate >= feat_rate

        # 3. serial vs overlapped end-to-end (KEYSTONE_PROFILE_DIR=...
        # captures a jax profiler trace of both passes)
        with maybe_trace("bench_ingest_imagenet"):
            t0 = time.perf_counter()
            data = ImageNetLoader.load(
                root, label_map, size=args.size, workers=16
            )
            for s in range(0, len(data.data), args.batch):
                jax.block_until_ready(
                    featurize(jnp.asarray(data.data[s : s + args.batch]))
                )
            serial = time.perf_counter() - t0

            t0 = time.perf_counter()
            n = 0
            for X, _y in ImageNetLoader.stream_batches(
                root, label_map, batch_size=args.batch, size=args.size,
                workers=16,
            ):
                jax.block_until_ready(featurize(jnp.asarray(X)))
                n += len(X)
            overlap = time.perf_counter() - t0
        assert n == args.images
        result["serial_seconds"] = round(serial, 2)
        result["overlapped_seconds"] = round(overlap, 2)
        result["overlap_speedup"] = round(serial / overlap, 2)
        result["images"] = args.images
        result["px"] = args.size
    print(json.dumps(result))


if __name__ == "__main__":
    main()
