"""ImageNet ingest throughput benchmark (SURVEY.md §7 hard part 4).

Generates a synthetic-JPEG synset tree, then measures:
1. decode -> NHWC rate (images/sec) of the PIL thread pool at 256px,
   swept over worker counts;
2. the featurization rate of a representative conv patch-extraction step
   on the default backend;
3. overlapped streaming (decode-ahead batches feeding featurization)
   vs serial decode-then-featurize.

Usage: python tools/bench_ingest.py [--images 512] [--size 256]
Prints one JSON line; paste the numbers into NOTES_r2.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_jpeg_tree(root: str, n_images: int, size: int, synsets: int = 8) -> dict:
    """Class-textured JPEGs in <synset>/ dirs; returns the label map."""
    from PIL import Image

    rng = np.random.default_rng(0)
    label_map = {}
    base, extra = divmod(n_images, synsets)
    for s in range(synsets):
        name = f"n{s:08d}"
        label_map[name] = s
        d = os.path.join(root, name)
        os.makedirs(d, exist_ok=True)
        per = base + (1 if s < extra else 0)  # remainder distributed
        for i in range(per):
            x = rng.uniform(size=(size, size, 3))
            yy, xx = np.mgrid[0:size, 0:size]
            x[..., 0] = 0.5 + 0.5 * np.sin(2 * np.pi * (s + 2) / size * xx)
            img = Image.fromarray((x * 255).astype(np.uint8))
            img.save(os.path.join(d, f"img_{i:05d}.JPEG"), quality=90)
    return label_map


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=512)
    ap.add_argument("--size", type=int, default=256)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--workers", type=int, nargs="+", default=[4, 8, 16, 32])
    args = ap.parse_args()

    from keystone_tpu.utils.platform import ensure_live_backend

    backend = ensure_live_backend()
    import jax
    import jax.numpy as jnp
    from jax import lax

    from keystone_tpu.loaders.imagenet import ImageNetLoader

    # The loader caps pool size at the core count (decode is CPU-bound;
    # NOTES_r2 §8's non-monotone sweep was oversubscription thrash on a
    # 1-core host), so requested counts above nproc clamp — the table
    # records the EFFECTIVE pool size.
    result: dict = {
        "metric": "imagenet_ingest",
        "backend": backend,
        "host_cores": os.cpu_count(),
    }
    with tempfile.TemporaryDirectory() as root:
        label_map = make_jpeg_tree(root, args.images, args.size)

        # 1. raw decode rate: PIL thread pool per worker count vs the
        # native libjpeg/OpenMP pool. Save/restore any user override.
        decode = {}
        prior = os.environ.get("KEYSTONE_JPEG_BACKEND")
        try:
            os.environ["KEYSTONE_JPEG_BACKEND"] = "pil"
            from keystone_tpu.loaders.imagenet import _pool_workers

            for w in args.workers:
                eff = _pool_workers(w)
                key = f"pil-{eff}"
                if key in decode:
                    continue  # clamped to an already-measured pool size
                t0 = time.perf_counter()
                data = ImageNetLoader.load(
                    root, label_map, size=args.size, workers=w
                )
                dt = time.perf_counter() - t0
                decode[key] = round(len(data.data) / dt, 1)
            from keystone_tpu import native

            if native.jpeg_available():
                os.environ["KEYSTONE_JPEG_BACKEND"] = "native"
                t0 = time.perf_counter()
                data = ImageNetLoader.load(root, label_map, size=args.size)
                decode["native"] = round(
                    len(data.data) / (time.perf_counter() - t0), 1
                )
        finally:
            if prior is None:
                os.environ.pop("KEYSTONE_JPEG_BACKEND", None)
            else:
                os.environ["KEYSTONE_JPEG_BACKEND"] = prior
        result["decode_images_per_sec"] = decode
        best_rate = max(decode.values())

        # 2. featurization rate: conv patch extraction + pool, the front of
        # the RandomPatchCifar/ImageNet featurization stack.
        filters = jnp.asarray(
            np.random.default_rng(0).normal(size=(6, 6, 3, 64)) * 0.1,
            dtype=jnp.float32,
        )

        @jax.jit
        def featurize(X):
            out = lax.conv_general_dilated(
                X, filters, (2, 2), "VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
            return jnp.maximum(out, 0.0).mean(axis=(1, 2))

        X0 = jnp.asarray(data.data[: args.batch])
        jax.block_until_ready(featurize(X0))  # compile
        t0 = time.perf_counter()
        reps = 5
        for _ in range(reps):
            jax.block_until_ready(featurize(X0))
        feat_rate = args.batch * reps / (time.perf_counter() - t0)
        result["featurize_images_per_sec"] = round(feat_rate, 1)
        result["decode_feeds_featurization"] = best_rate >= feat_rate

        # 3. serial vs overlapped end-to-end
        t0 = time.perf_counter()
        data = ImageNetLoader.load(root, label_map, size=args.size, workers=16)
        for s in range(0, len(data.data), args.batch):
            jax.block_until_ready(
                featurize(jnp.asarray(data.data[s : s + args.batch]))
            )
        serial = time.perf_counter() - t0

        t0 = time.perf_counter()
        n = 0
        for X, _y in ImageNetLoader.stream_batches(
            root, label_map, batch_size=args.batch, size=args.size, workers=16
        ):
            jax.block_until_ready(featurize(jnp.asarray(X)))
            n += len(X)
        overlap = time.perf_counter() - t0
        assert n == args.images
        result["serial_seconds"] = round(serial, 2)
        result["overlapped_seconds"] = round(overlap, 2)
        result["overlap_speedup"] = round(serial / overlap, 2)
        result["images"] = args.images
        result["px"] = args.size
    print(json.dumps(result))


if __name__ == "__main__":
    main()
