"""End-to-end tracing demo: a small fit + streamed solve + serve under
``KEYSTONE_TRACE=1``, exported as Chrome-trace JSON and schema-validated.

This is the ``make trace-demo`` target and the tier-1 observability
smoke: one run must produce spans covering every instrumented surface —
executor nodes (fit/apply, cache hit/miss), solver chunks (H2D +
accumulate + Cholesky), prefetch queue residency, and the serving request
lifecycle (queued → device → resolved) — plus a ``MetricsRegistry``
snapshot with serving latency percentiles. The exported file opens in
Perfetto (https://ui.perfetto.dev).

Usage: KEYSTONE_TRACE=1 python tools/trace_demo.py [--out trace.json]
Prints one JSON line: validation verdict, span-category coverage, and the
registry's serving latency snapshot. Exit 1 on any missing coverage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: Span categories (and one representative span each) a healthy traced
#: run must cover — the wiring contract this demo exists to prove.
REQUIRED_COVERAGE = {
    "executor": "node:",
    "pipeline": "pipeline.",
    "solver": "solve.",
    "stream": "prefetch.",
    "serving": "serve.",
}


def run_demo(out_path: str) -> dict:
    """Run the traced fit+serve and export/validate the trace. Forces
    ``config.trace`` on for its own scope (restored after), so it works
    both under ``KEYSTONE_TRACE=1`` and called in-process by the tier-1
    test."""
    from keystone_tpu.config import config
    from keystone_tpu.linalg import solve_least_squares_chunked
    from keystone_tpu.loaders.stream import BatchIterator
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.scalers import StandardScaler
    from keystone_tpu.utils.metrics import (
        active_tracer,
        metrics_registry,
        reset_tracer,
        validate_chrome_trace,
    )
    from keystone_tpu.workflow.serving import PipelineService

    prior_trace = config.trace
    config.trace = True
    reset_tracer()
    try:
        rng = np.random.default_rng(0)
        d, n = 8, 64
        X = rng.normal(size=(n, d)).astype(np.float32)
        Y = (X @ rng.normal(size=(d, 3))).astype(np.float32)

        # 1. fit + apply: executor node spans (miss on fit, hit on refit).
        pipe = StandardScaler().with_data(X).and_then(L2Normalizer())
        fitted = pipe.fit()
        fitted.apply(X).get()

        # 2. streamed normal-equations solve with prefetch: solver chunk
        # H2D/accumulate spans + prefetch produce/residency spans.
        solve_least_squares_chunked(
            BatchIterator.from_arrays(X, Y, batch_rows=16).prefetch(2),
            lam=1e-3,
        )

        # 3. serving: warmed engine + micro-batcher request lifecycle.
        # Fresh latency histograms so the reported snapshot describes THIS
        # demo run, not whatever the process served earlier.
        metrics_registry.histogram("serve.e2e_latency").reset()
        metrics_registry.histogram("serve.request_latency").reset()
        cp = fitted.compiled(max_batch=16)
        cp.warmup((d,))
        with PipelineService(cp, max_delay_ms=1.0) as svc:
            futs = [svc.submit(X[i % n]) for i in range(12)]
            for f in futs:
                f.result()
            service_stats = svc.stats()

        tracer = active_tracer()
        doc = tracer.export(out_path)
        errors = validate_chrome_trace(doc)
        spans = tracer.spans()
    finally:
        config.trace = prior_trace
        reset_tracer()

    by_cat: dict = {}
    for s in spans:
        by_cat.setdefault(s["cat"], set()).add(s["name"])
    coverage = {
        cat: sorted(names) for cat, names in sorted(by_cat.items())
    }
    missing = [
        cat for cat, prefix in REQUIRED_COVERAGE.items()
        if not any(n.startswith(prefix) for n in by_cat.get(cat, ()))
    ]
    snap = metrics_registry.snapshot()
    return {
        "metric": "trace_demo",
        "out": out_path,
        "events": len(doc["traceEvents"]),
        "schema_errors": errors,
        "coverage": coverage,
        "missing_coverage": missing,
        "serving_latency": snap["serve.e2e_latency"],
        "service_requests": service_stats["requests"],
        "ok": not errors and not missing,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/tmp/keystone_trace.json",
                    help="where to write the Chrome-trace JSON")
    args = ap.parse_args(argv)
    result = run_demo(args.out)
    print(json.dumps(result))
    if result["ok"]:
        print(f"open {args.out} in https://ui.perfetto.dev",
              file=sys.stderr)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
