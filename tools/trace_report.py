"""Report/validate a Chrome-trace JSON exported by ``Tracer.export()``.

Validates the document against the Chrome trace-event schema (the shared
``utils.metrics.validate_chrome_trace`` check — the same one the tier-1
trace-demo test runs, so the exporter and this CLI can't drift) and prints
a per-(category, name) aggregate table: span count, total/mean/max
duration. The file itself opens directly in Perfetto
(https://ui.perfetto.dev) or chrome://tracing for the timeline view.

A schema-valid trace with ZERO spans is treated as an ERROR, not an empty
table: it means the tracer was disabled (or never recorded), and a tool
that prints a clean empty summary over a dead tracer is a false green.

``--fit`` switches to the fit-attribution view: the executor's per-node
spans (``node:<label>``, cat ``executor``) aggregate into the SAME
attribution-table format ``tools/profile_report.py`` renders over a live
``ResourceProfile`` — wall time and cache tallies from the trace, the
cost-model columns printed as ``-`` (a trace carries no cost model) — so
a Chrome trace of a fit and a live profile of it read identically.

``--request <id>`` switches to the per-request critical-path view: every
span carrying that request id (``req_id`` on single-request spans,
membership in ``req_ids`` on group spans — serve.flush / serve.device),
including span trees tail-sampled into the export's ``tailSampled``
store after the ring churned past them, broken down into the journey's
phases: queue wait (submit → flush-group pop), device time (launch →
materialized), and the resolve tail.

``--telemetry DIR`` switches to the durable-telemetry view: every
``keystone_telemetry_*.jsonl`` segment in DIR (written by
``utils.telemetry.TelemetryLog`` — any number of daemon/trainer
processes) is merged into ONE Chrome-trace JSON on a shared wall-clock
timeline. Each segment opens with a ``meta`` record carrying a
``(unix_time, perf_ns)`` anchor pair, which is what maps each process's
monotonic ``perf_counter_ns`` stamps onto wall time — so journeys from
process A, the tracer span trees process B exported at close (its live
ring — the merge the module docstring promises), and the swap/refresh
lifecycle records all land on one timeline, every event keyed by its
wire trace id in ``args.trace_id``. ``--out FILE`` writes the merged
document (opens in Perfetto); stdout gets the per-trace-id index.

``--telemetry DIR --slo`` computes per-tenant/tier deadline-hit rate and
error-budget burn from the journey records instead: overall and over
rolling ``--window`` seconds buckets, against ``--target``. Same
good/excluded status semantics as the live ``/stats`` SLO block
(``utils.telemetry``): 5xx-family statuses burn budget, client errors
and admission refusals (400/403/429) stay out of the denominator.

Usage:
    python tools/trace_report.py TRACE.json [--validate-only] [--top N]
        [--request ID]
    python tools/trace_report.py --telemetry DIR [--out MERGED.json]
        [--slo --window S --target T]

Exit status: 0 = valid trace, 1 = schema problems / zero spans / unknown
request id / empty telemetry dir (listed on stderr).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def summarize(doc: dict) -> dict:
    """Aggregate X-phase events per (cat, name): count and duration stats
    (milliseconds)."""
    rows: dict = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        key = (ev.get("cat", ""), ev["name"])
        r = rows.setdefault(
            key, {"count": 0, "total_ms": 0.0, "max_ms": 0.0}
        )
        dur_ms = float(ev.get("dur", 0.0)) / 1e3
        r["count"] += 1
        r["total_ms"] += dur_ms
        if dur_ms > r["max_ms"]:
            r["max_ms"] = dur_ms
    return {
        f"{cat}/{name}": {
            "count": r["count"],
            "total_ms": round(r["total_ms"], 3),
            "mean_ms": round(r["total_ms"] / r["count"], 4),
            "max_ms": round(r["max_ms"], 3),
        }
        for (cat, name), r in sorted(rows.items())
    }


def fit_rows(doc: dict) -> list:
    """Executor node spans aggregated into profile-row shape (the
    ``ResourceProfile.rows()`` schema, measured columns only), heaviest
    wall first — the input ``render_attribution_table`` shares with the
    live profiler."""
    agg: dict = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") != "X" or ev.get("cat") != "executor":
            continue
        name = ev.get("name", "")
        if not name.startswith("node:"):
            continue
        label = name[len("node:"):]
        r = agg.setdefault(label, {"calls": 0, "wall_us": 0.0,
                                   "hits": 0, "executed": 0})
        r["calls"] += 1
        r["wall_us"] += float(ev.get("dur", 0.0))
        cache = (ev.get("args") or {}).get("cache")
        if cache in ("hit", "memo"):
            r["hits"] += 1
        else:
            r["executed"] += 1
    rows = [
        {
            "node": label,
            "calls": r["calls"],
            "wall_ms": round(r["wall_us"] / 1e3, 4),
            "device_wait_ms": None,
            "flops": None,
            "bytes_accessed": None,
            "output_bytes": None,
            "hbm_delta_bytes": None,
            "cache_hits": r["hits"],
            "executed": r["executed"],
            "provenance": "measured",
        }
        for label, r in agg.items()
    ]
    rows.sort(key=lambda r: -r["wall_ms"])
    return rows


def _mentions(ev: dict, rid: int) -> bool:
    args = ev.get("args") or {}
    return args.get("req_id") == rid or rid in (args.get("req_ids") or ())


def request_events(doc: dict, rid: int) -> list:
    """Every X event referencing request ``rid`` — from the live ring
    (traceEvents) plus the tail-sampled store — deduped and time-ordered."""
    events = [
        ev for ev in doc.get("traceEvents", [])
        if ev.get("ph") == "X" and _mentions(ev, rid)
    ]
    seen = {(ev["name"], ev.get("ts")) for ev in events}
    for ev in doc.get("tailSampled", {}).get(str(rid), []):
        if ev.get("ph") == "X" and (ev["name"], ev.get("ts")) not in seen:
            events.append(ev)
    events.sort(key=lambda ev: ev.get("ts", 0.0))
    return events


def request_report(doc: dict, rid: int) -> dict:
    """The critical-path breakdown of one request's journey: where its
    end-to-end latency went, phase by phase. Durations in milliseconds;
    ``resolve_ms`` is the tail between the device result materializing
    and the future resolving (slice + deliver + histogram work)."""
    events = request_events(doc, rid)
    by_name: dict = {}
    for ev in events:
        by_name.setdefault(ev["name"], []).append(ev)

    def total_ms(name):
        return sum(float(e.get("dur", 0.0)) for e in by_name.get(name, [])) / 1e3

    # serve.queued spans all start at the SUBMIT timestamp — a
    # re-dispatched request (replica death) gets one per flush-group pop,
    # and the intervals overlap. Real queue residency is the longest one
    # (submit -> final pop), not their sum.
    queued_ms = max(
        (float(e.get("dur", 0.0)) for e in by_name.get("serve.queued", [])),
        default=0.0,
    ) / 1e3
    device_ms = total_ms("serve.device")
    flush_ms = total_ms("serve.flush")
    req_spans = by_name.get("serve.request", [])
    e2e_ms = total_ms("serve.request")
    outcome = None
    for ev in req_spans:
        outcome = (ev.get("args") or {}).get("outcome", outcome)
    phases = {
        "queue_wait_ms": round(queued_ms, 4),
        "device_ms": round(device_ms, 4),
        "flush_ms": round(flush_ms, 4),
        "e2e_ms": round(e2e_ms, 4),
    }
    if e2e_ms:
        phases["resolve_tail_ms"] = round(
            max(0.0, e2e_ms - queued_ms - device_ms), 4
        )
    return {
        "request": rid,
        "outcome": outcome,
        "phases": phases,
        "spans": [
            {
                "name": ev["name"],
                "ts_ms": round(float(ev.get("ts", 0.0)) / 1e3, 4),
                "dur_ms": round(float(ev.get("dur", 0.0)) / 1e3, 4),
                "thread": ev.get("tid"),
                "args": ev.get("args") or {},
            }
            for ev in events
        ],
    }


# ---------------------------------------------------------------------------
# Durable-telemetry merge (utils.telemetry.TelemetryLog segments)
# ---------------------------------------------------------------------------


def load_telemetry(directory: str) -> tuple:
    """Every record from the directory's ``keystone_telemetry_*.jsonl``
    segments, each tagged (``_anchor``) with its segment's wall/perf
    anchor pair. Torn tail lines (a segment still being written) and
    foreign files are skipped, not fatal. Returns (records, paths)."""
    import glob

    records: list = []
    paths = sorted(glob.glob(
        os.path.join(directory, "keystone_telemetry_*.jsonl")
    ))
    for path in paths:
        anchor = None
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail of a live segment
                if not isinstance(rec, dict):
                    continue
                if rec.get("kind") == "meta":
                    anchor = rec.get("anchor")
                    continue
                rec["_anchor"] = anchor
                records.append(rec)
    return records, paths


def _wall_us(ns: float, anchor: dict) -> float:
    """A process-local ``perf_counter_ns`` stamp as wall-clock µs, via
    the segment's anchor pair. Without an anchor (foreign/damaged
    segment) the raw stamp degrades to µs — ordering within that process
    survives, cross-process alignment does not."""
    if not anchor:
        return ns / 1e3
    return (anchor["unix_time"] + (ns - anchor["perf_ns"]) / 1e9) * 1e6


def merge_telemetry(records: list) -> dict:
    """All journey / span / lifecycle records as ONE Chrome-trace doc on
    the shared wall-clock µs timeline, every event carrying its wire
    trace id in ``args.trace_id`` (the cross-process join key)."""
    events: list = []
    for rec in records:
        anchor = rec.get("_anchor")
        kind = rec.get("kind")
        pid = rec.get("pid", 0)
        if kind == "journey":
            j = rec.get("journey") or {}
            phases = j.get("phases") or []
            if not phases:
                continue
            t0, t1 = phases[0]["t_ns"], phases[-1]["t_ns"]
            meta = j.get("meta") or {}
            args = {
                "trace_id": rec.get("trace_id"),
                "req_id": j.get("id"),
                "outcome": j.get("outcome"),
                "service": rec.get("service"),
            }
            for k in ("tenant", "tier", "status", "generation"):
                if k in meta:
                    args[k] = meta[k]
            events.append({
                "name": f"journey:{rec.get('service')}", "cat": "journey",
                "ph": "X", "ts": _wall_us(t0, anchor),
                "dur": max(0.0, (t1 - t0) / 1e3), "pid": pid, "tid": 0,
                "args": args,
            })
            # Per-phase legs: where inside the journey the time went.
            for p0, p1 in zip(phases, phases[1:]):
                events.append({
                    "name": f"phase:{p0['phase']}->{p1['phase']}",
                    "cat": "journey", "ph": "X",
                    "ts": _wall_us(p0["t_ns"], anchor),
                    "dur": max(0.0, (p1["t_ns"] - p0["t_ns"]) / 1e3),
                    "pid": pid, "tid": 0,
                    "args": {"trace_id": rec.get("trace_id"),
                             "req_id": j.get("id")},
                })
        elif kind == "spans":
            for s in rec.get("events") or []:
                events.append({
                    "name": s["name"], "cat": s.get("cat", ""),
                    "ph": "X", "ts": _wall_us(s["start_ns"], anchor),
                    "dur": s.get("dur_ns", 0) / 1e3, "pid": pid,
                    "tid": s.get("tid") or 0, "args": s.get("args") or {},
                })
        elif kind in ("swap", "refresh"):
            t0 = rec.get("start_ns")
            if t0 is None:
                continue
            t1 = rec.get("end_ns", t0)
            args = {
                k: rec[k]
                for k in ("trace_id", "service", "generation",
                          "from_generation", "seq", "artifact",
                          "fingerprint")
                if rec.get(k) is not None
            }
            events.append({
                "name": f"{kind}:{rec.get('service')}", "cat": "lifecycle",
                "ph": "X", "ts": _wall_us(t0, anchor),
                "dur": max(0.0, (t1 - t0) / 1e3), "pid": pid, "tid": 0,
                "args": args,
            })
    events.sort(key=lambda ev: ev["ts"])
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def trace_index(doc: dict) -> dict:
    """Per-trace-id digest of a merged document: how many events, which
    processes/services a trace crossed, its wall window, and the journey
    outcome(s) — the offline answer to "what happened to request X"."""
    by: dict = {}
    for ev in doc.get("traceEvents", []):
        args = ev.get("args") or {}
        tid = args.get("trace_id")
        if not tid:
            continue
        e = by.setdefault(tid, {
            "events": 0, "pids": set(), "services": set(),
            "first_ts_us": ev["ts"], "last_ts_us": ev["ts"],
            "outcomes": [],
        })
        e["events"] += 1
        e["pids"].add(ev.get("pid"))
        if args.get("service"):
            e["services"].add(args["service"])
        e["first_ts_us"] = min(e["first_ts_us"], ev["ts"])
        e["last_ts_us"] = max(
            e["last_ts_us"], ev["ts"] + float(ev.get("dur", 0.0))
        )
        if ev.get("cat") == "journey" and args.get("outcome"):
            e["outcomes"].append(args["outcome"])
    return {
        tid: {
            "events": e["events"],
            "pids": sorted(p for p in e["pids"] if p is not None),
            "services": sorted(e["services"]),
            "span_ms": round((e["last_ts_us"] - e["first_ts_us"]) / 1e3, 3),
            "outcomes": e["outcomes"],
        }
        for tid, e in sorted(by.items())
    }


def slo_report(records: list, window_s: float, target: float) -> dict:
    """Per-tenant/tier deadline-hit rate + error-budget burn from the
    journey records: overall, and per rolling ``window_s`` bucket (0 =
    one bucket over everything). Status semantics shared with the live
    accounting (``utils.telemetry``)."""
    from keystone_tpu.utils.telemetry import (
        SLO_BAD_STATUSES,
        SLO_EXCLUDED_STATUSES,
    )

    events: list = []  # (wall_s, tenant, tier, good)
    for rec in records:
        if rec.get("kind") != "journey":
            continue
        j = rec.get("journey") or {}
        meta = j.get("meta") or {}
        status = meta.get("status")
        phases = j.get("phases") or []
        if status is None or not phases:
            continue
        if int(status) in SLO_EXCLUDED_STATUSES:
            continue
        wall = _wall_us(phases[-1]["t_ns"], rec.get("_anchor")) / 1e6
        events.append((
            wall,
            meta.get("tenant") or "anonymous",
            meta.get("tier") or "best_effort",
            int(status) not in SLO_BAD_STATUSES,
        ))
    out = {
        "window_s": window_s, "target": target,
        "events": len(events), "tenants": {}, "windows": [],
    }
    if not events:
        return out
    events.sort()
    t_lo = events[0][0]
    budget = max(1e-9, 1.0 - target)

    def entry(tally):
        total, good = tally
        hit = good / total
        return {
            "total": total, "good": good,
            "hit_rate": round(hit, 6),
            "burn": round((1.0 - hit) / budget, 4),
        }

    overall: dict = {}
    buckets: dict = {}
    for wall, tenant, tier, good in events:
        w = int((wall - t_lo) // window_s) if window_s > 0 else 0
        for store in (overall, buckets.setdefault(w, {})):
            tally = store.setdefault((tenant, tier), [0, 0])
            tally[0] += 1
            tally[1] += int(good)
    for (tenant, tier), tally in sorted(overall.items()):
        out["tenants"].setdefault(tenant, {})[tier] = entry(tally)
    for w in sorted(buckets):
        row: dict = {
            "window": w,
            "start_unix": round(t_lo + w * window_s, 3),
            "tenants": {},
        }
        for (tenant, tier), tally in sorted(buckets[w].items()):
            row["tenants"].setdefault(tenant, {})[tier] = entry(tally)
        out["windows"].append(row)
    return out


def _telemetry_main(args) -> int:
    from keystone_tpu.utils.metrics import validate_chrome_trace

    records, paths = load_telemetry(args.telemetry)
    if not records:
        print(
            f"EMPTY: no telemetry records under {args.telemetry} "
            f"({len(paths)} segment file(s)) — was KEYSTONE_TELEMETRY_DIR "
            "set for the recorded run?",
            file=sys.stderr,
        )
        return 1
    if args.slo:
        rep = slo_report(records, args.window, args.target)
        print(json.dumps(rep))
        for tenant, tiers in rep["tenants"].items():
            for tier, e in tiers.items():
                print(
                    f"{tenant}/{tier}: hit_rate={e['hit_rate']} "
                    f"burn={e['burn']} ({e['good']}/{e['total']} over "
                    f"{len(rep['windows'])} window(s))",
                    file=sys.stderr,
                )
        return 0
    doc = merge_telemetry(records)
    errors = validate_chrome_trace(doc)
    if errors:
        for e in errors[:20]:
            print(f"INVALID: {e}", file=sys.stderr)
        return 1
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f)
    index = trace_index(doc)
    print(json.dumps({
        "telemetry": args.telemetry,
        "segments": len(paths),
        "events": len(doc["traceEvents"]),
        "merged": args.out,
        "traces": index,
    }))
    if index:
        w = max(len(t) for t in index)
        print(
            f"\n{'trace':<{w}}  {'events':>6}  {'procs':>5}  "
            f"{'span ms':>9}  services / outcomes",
            file=sys.stderr,
        )
        for tid, e in index.items():
            print(
                f"{tid:<{w}}  {e['events']:>6}  {len(e['pids']):>5}  "
                f"{e['span_ms']:>9.3f}  "
                f"{','.join(e['services'])} / {','.join(e['outcomes'])}",
                file=sys.stderr,
            )
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("trace", nargs="?", default=None,
                    help="Chrome-trace JSON file (Tracer.export)")
    ap.add_argument("--validate-only", action="store_true",
                    help="schema check only, no summary table")
    ap.add_argument("--top", type=int, default=0,
                    help="only the N rows with the largest total time")
    ap.add_argument("--request", type=int, default=None, metavar="ID",
                    help="critical-path view of one request id instead of "
                         "the aggregate table")
    ap.add_argument("--fit", action="store_true",
                    help="aggregate executor node spans into the "
                         "profile_report attribution-table format")
    ap.add_argument("--telemetry", default=None, metavar="DIR",
                    help="merge a KEYSTONE_TELEMETRY_DIR's JSONL segments "
                         "(multi-process) into one wall-clock Chrome trace "
                         "keyed by trace id, instead of reading a trace "
                         "file")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="with --telemetry: write the merged Chrome-trace "
                         "JSON here (opens in Perfetto)")
    ap.add_argument("--slo", action="store_true",
                    help="with --telemetry: per-tenant/tier deadline-hit "
                         "rate and error-budget burn from the journey "
                         "records")
    ap.add_argument("--window", type=float, default=None, metavar="S",
                    help="--slo rolling window seconds (default "
                         "KEYSTONE_SLO_WINDOW_S; 0 = one window)")
    ap.add_argument("--target", type=float, default=None,
                    help="--slo hit-rate target (default "
                         "KEYSTONE_SLO_TARGET)")
    args = ap.parse_args(argv)

    if args.telemetry is not None:
        from keystone_tpu.config import config

        if args.window is None:
            args.window = config.slo_window_s
        if args.target is None:
            args.target = config.slo_target
        return _telemetry_main(args)
    if args.trace is None:
        ap.error("pass a trace file, or --telemetry DIR")

    from keystone_tpu.utils.metrics import validate_chrome_trace

    with open(args.trace) as f:
        doc = json.load(f)
    errors = validate_chrome_trace(doc)
    if errors:
        for e in errors[:20]:
            print(f"INVALID: {e}", file=sys.stderr)
        if len(errors) > 20:
            print(f"... and {len(errors) - 20} more", file=sys.stderr)
        return 1
    n_spans = sum(
        1 for ev in doc.get("traceEvents", []) if ev.get("ph") == "X"
    )
    if n_spans == 0:
        # A dead tracer must fail loudly, not produce a green empty table.
        print(
            f"EMPTY: {args.trace} is schema-valid but contains zero spans "
            "— was KEYSTONE_TRACE=1 set for the traced run?",
            file=sys.stderr,
        )
        return 1
    if args.validate_only:
        print(json.dumps({
            "trace": args.trace, "valid": True,
            "events": len(doc["traceEvents"]),
        }))
        return 0

    if args.fit:
        rows = fit_rows(doc)
        if not rows:
            # Same loud-failure rule as the zero-span gate: a trace with
            # no executor node spans cannot attribute a fit.
            print(
                f"NOT FOUND: {args.trace} contains no executor node spans "
                "— was the traced run a fit/apply?",
                file=sys.stderr,
            )
            return 1
        from keystone_tpu.utils.metrics import render_attribution_table

        print(json.dumps({"trace": args.trace, "nodes": rows}))
        print("\n" + render_attribution_table(rows), file=sys.stderr)
        return 0

    if args.request is not None:
        rep = request_report(doc, args.request)
        if not rep["spans"]:
            print(
                f"NOT FOUND: no spans reference request id {args.request} "
                "(the ring may have churned past it and it was not "
                "tail-sampled)",
                file=sys.stderr,
            )
            return 1
        print(json.dumps(rep))
        ph = rep["phases"]
        print(
            f"\nrequest {args.request}  outcome={rep['outcome']}",
            file=sys.stderr,
        )
        for key in ("queue_wait_ms", "device_ms", "flush_ms",
                    "resolve_tail_ms", "e2e_ms"):
            if key in ph:
                print(f"  {key:<16} {ph[key]:>10.4f}", file=sys.stderr)
        w = max(len(s["name"]) for s in rep["spans"])
        print(f"\n{'span':<{w}}  {'ts ms':>10}  {'dur ms':>9}  thread",
              file=sys.stderr)
        for s in rep["spans"]:
            print(f"{s['name']:<{w}}  {s['ts_ms']:>10.4f}  "
                  f"{s['dur_ms']:>9.4f}  {s['thread']}", file=sys.stderr)
        return 0

    rows = summarize(doc)
    if args.top > 0:
        rows = dict(sorted(
            rows.items(), key=lambda kv: -kv[1]["total_ms"]
        )[: args.top])
    print(json.dumps({
        "trace": args.trace, "valid": True,
        "events": len(doc["traceEvents"]), "spans": rows,
    }))
    if rows:
        w = max(len(k) for k in rows)
        print(f"\n{'span':<{w}}  {'count':>7}  {'total ms':>10}  "
              f"{'mean ms':>9}  {'max ms':>9}", file=sys.stderr)
        for k, r in rows.items():
            print(f"{k:<{w}}  {r['count']:>7}  {r['total_ms']:>10.3f}  "
                  f"{r['mean_ms']:>9.4f}  {r['max_ms']:>9.3f}",
                  file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
