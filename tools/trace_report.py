"""Report/validate a Chrome-trace JSON exported by ``Tracer.export()``.

Validates the document against the Chrome trace-event schema (the shared
``utils.metrics.validate_chrome_trace`` check — the same one the tier-1
trace-demo test runs, so the exporter and this CLI can't drift) and prints
a per-(category, name) aggregate table: span count, total/mean/max
duration. The file itself opens directly in Perfetto
(https://ui.perfetto.dev) or chrome://tracing for the timeline view.

A schema-valid trace with ZERO spans is treated as an ERROR, not an empty
table: it means the tracer was disabled (or never recorded), and a tool
that prints a clean empty summary over a dead tracer is a false green.

``--fit`` switches to the fit-attribution view: the executor's per-node
spans (``node:<label>``, cat ``executor``) aggregate into the SAME
attribution-table format ``tools/profile_report.py`` renders over a live
``ResourceProfile`` — wall time and cache tallies from the trace, the
cost-model columns printed as ``-`` (a trace carries no cost model) — so
a Chrome trace of a fit and a live profile of it read identically.

``--request <id>`` switches to the per-request critical-path view: every
span carrying that request id (``req_id`` on single-request spans,
membership in ``req_ids`` on group spans — serve.flush / serve.device),
including span trees tail-sampled into the export's ``tailSampled``
store after the ring churned past them, broken down into the journey's
phases: queue wait (submit → flush-group pop), device time (launch →
materialized), and the resolve tail.

Usage:
    python tools/trace_report.py TRACE.json [--validate-only] [--top N]
        [--request ID]

Exit status: 0 = valid trace, 1 = schema problems / zero spans / unknown
request id (listed on stderr).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def summarize(doc: dict) -> dict:
    """Aggregate X-phase events per (cat, name): count and duration stats
    (milliseconds)."""
    rows: dict = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        key = (ev.get("cat", ""), ev["name"])
        r = rows.setdefault(
            key, {"count": 0, "total_ms": 0.0, "max_ms": 0.0}
        )
        dur_ms = float(ev.get("dur", 0.0)) / 1e3
        r["count"] += 1
        r["total_ms"] += dur_ms
        if dur_ms > r["max_ms"]:
            r["max_ms"] = dur_ms
    return {
        f"{cat}/{name}": {
            "count": r["count"],
            "total_ms": round(r["total_ms"], 3),
            "mean_ms": round(r["total_ms"] / r["count"], 4),
            "max_ms": round(r["max_ms"], 3),
        }
        for (cat, name), r in sorted(rows.items())
    }


def fit_rows(doc: dict) -> list:
    """Executor node spans aggregated into profile-row shape (the
    ``ResourceProfile.rows()`` schema, measured columns only), heaviest
    wall first — the input ``render_attribution_table`` shares with the
    live profiler."""
    agg: dict = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") != "X" or ev.get("cat") != "executor":
            continue
        name = ev.get("name", "")
        if not name.startswith("node:"):
            continue
        label = name[len("node:"):]
        r = agg.setdefault(label, {"calls": 0, "wall_us": 0.0,
                                   "hits": 0, "executed": 0})
        r["calls"] += 1
        r["wall_us"] += float(ev.get("dur", 0.0))
        cache = (ev.get("args") or {}).get("cache")
        if cache in ("hit", "memo"):
            r["hits"] += 1
        else:
            r["executed"] += 1
    rows = [
        {
            "node": label,
            "calls": r["calls"],
            "wall_ms": round(r["wall_us"] / 1e3, 4),
            "device_wait_ms": None,
            "flops": None,
            "bytes_accessed": None,
            "output_bytes": None,
            "hbm_delta_bytes": None,
            "cache_hits": r["hits"],
            "executed": r["executed"],
            "provenance": "measured",
        }
        for label, r in agg.items()
    ]
    rows.sort(key=lambda r: -r["wall_ms"])
    return rows


def _mentions(ev: dict, rid: int) -> bool:
    args = ev.get("args") or {}
    return args.get("req_id") == rid or rid in (args.get("req_ids") or ())


def request_events(doc: dict, rid: int) -> list:
    """Every X event referencing request ``rid`` — from the live ring
    (traceEvents) plus the tail-sampled store — deduped and time-ordered."""
    events = [
        ev for ev in doc.get("traceEvents", [])
        if ev.get("ph") == "X" and _mentions(ev, rid)
    ]
    seen = {(ev["name"], ev.get("ts")) for ev in events}
    for ev in doc.get("tailSampled", {}).get(str(rid), []):
        if ev.get("ph") == "X" and (ev["name"], ev.get("ts")) not in seen:
            events.append(ev)
    events.sort(key=lambda ev: ev.get("ts", 0.0))
    return events


def request_report(doc: dict, rid: int) -> dict:
    """The critical-path breakdown of one request's journey: where its
    end-to-end latency went, phase by phase. Durations in milliseconds;
    ``resolve_ms`` is the tail between the device result materializing
    and the future resolving (slice + deliver + histogram work)."""
    events = request_events(doc, rid)
    by_name: dict = {}
    for ev in events:
        by_name.setdefault(ev["name"], []).append(ev)

    def total_ms(name):
        return sum(float(e.get("dur", 0.0)) for e in by_name.get(name, [])) / 1e3

    # serve.queued spans all start at the SUBMIT timestamp — a
    # re-dispatched request (replica death) gets one per flush-group pop,
    # and the intervals overlap. Real queue residency is the longest one
    # (submit -> final pop), not their sum.
    queued_ms = max(
        (float(e.get("dur", 0.0)) for e in by_name.get("serve.queued", [])),
        default=0.0,
    ) / 1e3
    device_ms = total_ms("serve.device")
    flush_ms = total_ms("serve.flush")
    req_spans = by_name.get("serve.request", [])
    e2e_ms = total_ms("serve.request")
    outcome = None
    for ev in req_spans:
        outcome = (ev.get("args") or {}).get("outcome", outcome)
    phases = {
        "queue_wait_ms": round(queued_ms, 4),
        "device_ms": round(device_ms, 4),
        "flush_ms": round(flush_ms, 4),
        "e2e_ms": round(e2e_ms, 4),
    }
    if e2e_ms:
        phases["resolve_tail_ms"] = round(
            max(0.0, e2e_ms - queued_ms - device_ms), 4
        )
    return {
        "request": rid,
        "outcome": outcome,
        "phases": phases,
        "spans": [
            {
                "name": ev["name"],
                "ts_ms": round(float(ev.get("ts", 0.0)) / 1e3, 4),
                "dur_ms": round(float(ev.get("dur", 0.0)) / 1e3, 4),
                "thread": ev.get("tid"),
                "args": ev.get("args") or {},
            }
            for ev in events
        ],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("trace", help="Chrome-trace JSON file (Tracer.export)")
    ap.add_argument("--validate-only", action="store_true",
                    help="schema check only, no summary table")
    ap.add_argument("--top", type=int, default=0,
                    help="only the N rows with the largest total time")
    ap.add_argument("--request", type=int, default=None, metavar="ID",
                    help="critical-path view of one request id instead of "
                         "the aggregate table")
    ap.add_argument("--fit", action="store_true",
                    help="aggregate executor node spans into the "
                         "profile_report attribution-table format")
    args = ap.parse_args(argv)

    from keystone_tpu.utils.metrics import validate_chrome_trace

    with open(args.trace) as f:
        doc = json.load(f)
    errors = validate_chrome_trace(doc)
    if errors:
        for e in errors[:20]:
            print(f"INVALID: {e}", file=sys.stderr)
        if len(errors) > 20:
            print(f"... and {len(errors) - 20} more", file=sys.stderr)
        return 1
    n_spans = sum(
        1 for ev in doc.get("traceEvents", []) if ev.get("ph") == "X"
    )
    if n_spans == 0:
        # A dead tracer must fail loudly, not produce a green empty table.
        print(
            f"EMPTY: {args.trace} is schema-valid but contains zero spans "
            "— was KEYSTONE_TRACE=1 set for the traced run?",
            file=sys.stderr,
        )
        return 1
    if args.validate_only:
        print(json.dumps({
            "trace": args.trace, "valid": True,
            "events": len(doc["traceEvents"]),
        }))
        return 0

    if args.fit:
        rows = fit_rows(doc)
        if not rows:
            # Same loud-failure rule as the zero-span gate: a trace with
            # no executor node spans cannot attribute a fit.
            print(
                f"NOT FOUND: {args.trace} contains no executor node spans "
                "— was the traced run a fit/apply?",
                file=sys.stderr,
            )
            return 1
        from keystone_tpu.utils.metrics import render_attribution_table

        print(json.dumps({"trace": args.trace, "nodes": rows}))
        print("\n" + render_attribution_table(rows), file=sys.stderr)
        return 0

    if args.request is not None:
        rep = request_report(doc, args.request)
        if not rep["spans"]:
            print(
                f"NOT FOUND: no spans reference request id {args.request} "
                "(the ring may have churned past it and it was not "
                "tail-sampled)",
                file=sys.stderr,
            )
            return 1
        print(json.dumps(rep))
        ph = rep["phases"]
        print(
            f"\nrequest {args.request}  outcome={rep['outcome']}",
            file=sys.stderr,
        )
        for key in ("queue_wait_ms", "device_ms", "flush_ms",
                    "resolve_tail_ms", "e2e_ms"):
            if key in ph:
                print(f"  {key:<16} {ph[key]:>10.4f}", file=sys.stderr)
        w = max(len(s["name"]) for s in rep["spans"])
        print(f"\n{'span':<{w}}  {'ts ms':>10}  {'dur ms':>9}  thread",
              file=sys.stderr)
        for s in rep["spans"]:
            print(f"{s['name']:<{w}}  {s['ts_ms']:>10.4f}  "
                  f"{s['dur_ms']:>9.4f}  {s['thread']}", file=sys.stderr)
        return 0

    rows = summarize(doc)
    if args.top > 0:
        rows = dict(sorted(
            rows.items(), key=lambda kv: -kv[1]["total_ms"]
        )[: args.top])
    print(json.dumps({
        "trace": args.trace, "valid": True,
        "events": len(doc["traceEvents"]), "spans": rows,
    }))
    if rows:
        w = max(len(k) for k in rows)
        print(f"\n{'span':<{w}}  {'count':>7}  {'total ms':>10}  "
              f"{'mean ms':>9}  {'max ms':>9}", file=sys.stderr)
        for k, r in rows.items():
            print(f"{k:<{w}}  {r['count']:>7}  {r['total_ms']:>10.3f}  "
                  f"{r['mean_ms']:>9.4f}  {r['max_ms']:>9.3f}",
                  file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
