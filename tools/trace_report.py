"""Report/validate a Chrome-trace JSON exported by ``Tracer.export()``.

Validates the document against the Chrome trace-event schema (the shared
``utils.metrics.validate_chrome_trace`` check — the same one the tier-1
trace-demo test runs, so the exporter and this CLI can't drift) and prints
a per-(category, name) aggregate table: span count, total/mean/max
duration. The file itself opens directly in Perfetto
(https://ui.perfetto.dev) or chrome://tracing for the timeline view.

Usage:
    python tools/trace_report.py TRACE.json [--validate-only] [--top N]

Exit status: 0 = valid trace, 1 = schema problems (listed on stderr).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def summarize(doc: dict) -> dict:
    """Aggregate X-phase events per (cat, name): count and duration stats
    (milliseconds)."""
    rows: dict = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        key = (ev.get("cat", ""), ev["name"])
        r = rows.setdefault(
            key, {"count": 0, "total_ms": 0.0, "max_ms": 0.0}
        )
        dur_ms = float(ev.get("dur", 0.0)) / 1e3
        r["count"] += 1
        r["total_ms"] += dur_ms
        if dur_ms > r["max_ms"]:
            r["max_ms"] = dur_ms
    return {
        f"{cat}/{name}": {
            "count": r["count"],
            "total_ms": round(r["total_ms"], 3),
            "mean_ms": round(r["total_ms"] / r["count"], 4),
            "max_ms": round(r["max_ms"], 3),
        }
        for (cat, name), r in sorted(rows.items())
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("trace", help="Chrome-trace JSON file (Tracer.export)")
    ap.add_argument("--validate-only", action="store_true",
                    help="schema check only, no summary table")
    ap.add_argument("--top", type=int, default=0,
                    help="only the N rows with the largest total time")
    args = ap.parse_args(argv)

    from keystone_tpu.utils.metrics import validate_chrome_trace

    with open(args.trace) as f:
        doc = json.load(f)
    errors = validate_chrome_trace(doc)
    if errors:
        for e in errors[:20]:
            print(f"INVALID: {e}", file=sys.stderr)
        if len(errors) > 20:
            print(f"... and {len(errors) - 20} more", file=sys.stderr)
        return 1
    if args.validate_only:
        print(json.dumps({
            "trace": args.trace, "valid": True,
            "events": len(doc["traceEvents"]),
        }))
        return 0

    rows = summarize(doc)
    if args.top > 0:
        rows = dict(sorted(
            rows.items(), key=lambda kv: -kv[1]["total_ms"]
        )[: args.top])
    print(json.dumps({
        "trace": args.trace, "valid": True,
        "events": len(doc["traceEvents"]), "spans": rows,
    }))
    if rows:
        w = max(len(k) for k in rows)
        print(f"\n{'span':<{w}}  {'count':>7}  {'total ms':>10}  "
              f"{'mean ms':>9}  {'max ms':>9}", file=sys.stderr)
        for k, r in rows.items():
            print(f"{k:<{w}}  {r['count']:>7}  {r['total_ms']:>10.3f}  "
                  f"{r['mean_ms']:>9.4f}  {r['max_ms']:>9.3f}",
                  file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
