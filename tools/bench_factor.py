"""Factorization microbenchmark — where the BCD epoch's non-gemm time goes.

The round-3 solver rework replaced the per-epoch Cholesky solve with a
one-time explicit ridge inverse (NOTES_r3 §2) on the theory that TPU
lowers triangular solves sequentially while the inverse's per-epoch
apply is one MXU gemm. This tool measures the actual primitive costs on
the live backend so the tradeoff is grounded in silicon numbers, not
theory:

  gram        (n,b)ᵀ(n,b) gemm           — the MXU reference point
  cholesky    chol(b,b)                   — one-time, sequential lowering
  trsm_wide   inverse formation: two (b,b)×(b,b) triangular solves
  trsm_skinny cho_solve against k rhs     — the OLD per-epoch cost
  inv_gemm    (b,b)×(b,k) gemm            — the NEW per-epoch cost

Explicit inverse wins when
  trsm_wide < epochs · (trsm_skinny − inv_gemm),
i.e. above a break-even epoch count this tool prints per block size.

Usage: python tools/bench_factor.py [--blocks 1024 2048 4096 8192]
Prints one JSON line; paste into NOTES_r3.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time(fn, *args, reps: int = 3) -> float:
    import jax

    out = fn(*args)  # compile + warm-up
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
        jax.block_until_ready(out)
        # Force a ONE-ELEMENT host fetch — relay timing discipline (see
        # bench.py). Fetching the whole array would time the transport of
        # (b,b) outputs but not (b,k) ones and skew the break-even.
        float(jax.tree_util.tree_leaves(out)[0].ravel()[0])
    return (time.perf_counter() - t0) / reps


def measure_block(b: int, n: int, k: int) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.scipy.linalg import cho_solve, solve_triangular

    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.normal(size=(n, b)).astype(np.float32) / np.sqrt(n))
    rhs = jnp.asarray(rng.normal(size=(b, k)).astype(np.float32))
    eye = jnp.eye(b, dtype=jnp.float32)

    gram_fn = jax.jit(lambda x: x.T @ x + 1e-3 * eye)
    chol_fn = jax.jit(jnp.linalg.cholesky)
    inv_fn = jax.jit(
        lambda L: solve_triangular(
            L, solve_triangular(L, eye, lower=True), lower=True, trans=1
        )
    )
    skinny_fn = jax.jit(lambda L, r: cho_solve((L, True), r))
    gemm_fn = jax.jit(lambda M, r: M @ r)

    gram = gram_fn(a)
    L = chol_fn(gram)
    inv = inv_fn(L)

    t_gram = _time(gram_fn, a)
    t_chol = _time(chol_fn, gram)
    t_wide = _time(inv_fn, L)
    t_skinny = _time(skinny_fn, L, rhs)
    t_gemm = _time(gemm_fn, inv, rhs)

    saving = t_skinny - t_gemm
    breakeven = (t_wide / saving) if saving > 1e-9 else float("inf")
    gram_tflops = 2.0 * n * b * b / t_gram / 1e12
    return {
        "block": b,
        "gram_s": round(t_gram, 5),
        "gram_tflops": round(gram_tflops, 2),
        "cholesky_s": round(t_chol, 5),
        "trsm_wide_s": round(t_wide, 5),
        "trsm_skinny_s": round(t_skinny, 6),
        "inv_gemm_s": round(t_gemm, 6),
        "breakeven_epochs": (
            round(breakeven, 1) if breakeven != float("inf") else None
        ),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--blocks", type=int, nargs="+", default=[1024, 2048, 4096, 8192]
    )
    ap.add_argument("--n", type=int, default=32768)
    ap.add_argument("--k", type=int, default=16)
    args = ap.parse_args()

    from keystone_tpu.utils.platform import ensure_live_backend

    backend = ensure_live_backend()
    rows = [measure_block(b, args.n, args.k) for b in args.blocks]
    print(
        json.dumps(
            {"metric": "bcd_factorization_primitives", "backend": backend,
             "rows": rows}
        )
    )


if __name__ == "__main__":
    main()
