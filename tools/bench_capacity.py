"""Capacity-loop A/B benchmark: learned latency model off vs on.

Drives the SAME shifting-mix flood twice through the real socket +
HTTP ingress of a fresh ``ServingDaemon`` per phase — once with the
learned capacity model disabled (``KEYSTONE_CAPACITY_MODEL=0``, the
PR-19 baseline) and once enabled — and hard-gates the closed loop the
model is supposed to close:

1. **goodput_improved** — model-on goodput beats model-off. Goodput
   counts DEADLINE-MET 200s only (a late 200 is a served SLA
   violation — the exact waste class the model exists to prevent, so
   crediting it would rig the baseline). The mechanism: under
   sustained best-effort overload with a deadline that is infeasible
   at full queue depth, the baseline admits everything — most of it
   expires in the queue (504) and over half of what IS dispatched
   completes after its deadline (wasted device work) — while the
   model fast-fails the knowably-infeasible excess (429
   ``predicted_infeasible`` before any device work: effective-bucket
   pricing at the observed rows-per-flush drain rate, flush cost at
   the model's ``ADMIT_Q`` quantile) so the queue stabilises at a
   depth the admitted requests can actually survive. Clients back off
   exponentially on consecutive non-200s (identical policy in both
   phases — the realistic retry loop is what turns a fast-fail 429
   into freed capacity instead of a hammering retry storm).
2. **gold_p99_ok** — the gold tier's closed-loop p99 with the model
   on stays equal-or-better (a small tolerance covers timer noise):
   shedding doomed best-effort work must not cost the protected tier.
3. **zero_knowing_violations** — no request is both predicted
   infeasible and admitted: every ``predicted_infeasible`` journey in
   the on-phase telemetry must have been refused BEFORE admission
   (no ``admitted``/``dispatched`` phase stamp).
4. **microbatches_formed** — the deadline-aware cross-tenant
   micro-batcher coalesced at least one best-effort request into a
   gold group's padding slack during the flood. A small dedicated
   pool of LOOSE-deadline riders (per-request ``deadline_ms`` wide
   enough to survive the combined batch's p99) supplies eligible
   passengers — the tight flood class is never coalescible, which is
   itself the deadline-awareness under test.
5. **model_reacted** — the traffic mix shifts halfway through the
   flood (best-effort rows 1 -> 2) and the re-plan loop must notice:
   at least one executed or suppressed re-plan decision.

The best-effort deadline is **self-calibrating**: a throwaway daemon
measures the shallow-queue p50 (feasible floor) and the full-depth
p50 (infeasible ceiling) through the same wire, and the deadline is
set to their geometric midpoint — infeasible at depth, comfortably
feasible shallow — so the A/B contrast survives host-speed variance.

The ``serve_capacity`` row appends to BENCH_serve.json (one latest
row per metric) and is judged by ``make bench-watch`` like every
other serving row: goodput/per_s leaves down or p99/_ms leaves up
across rounds is a regression; the ``pass`` gate flags flipping
true -> false is a regression.

Usage: JAX_PLATFORMS=cpu python tools/bench_capacity.py \
           [--flood-seconds 4.0] [--out BENCH_serve.json]
Prints one JSON line; exit 0 iff every gate passed.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import math
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


GOLD_ROWS = 3       #: gold request rows: pads to bucket 4 with slack 1
BE_ROWS_A = 1       #: best-effort rows, first half of the flood
BE_ROWS_B = 2       #: best-effort rows, second half (the mix shift)
MAX_ROWS = 4        #: per-flush device rows — the capacity limiter
MAX_BATCH = 8       #: top bucket (pow-2 ladder (1, 2, 4, 8), unpinned)


def _closed_loop(port, sd, payload, stop_t, on_response, backoff_s=0.0):
    """One closed-loop client against the framed socket: send, classify
    (the callback gets the attempt's own deadline so a LATE 200 can be
    told apart from a deadline-met one), back off EXPONENTIALLY on
    consecutive non-200s (the realistic retry policy — identical in
    both phases; it is what turns a fast-fail 429 into freed capacity
    instead of a hammering retry storm), repeat until the stop time."""
    sc = sd.SocketClient(port)
    delay = backoff_s
    try:
        while time.perf_counter() < stop_t:
            doc = payload() if callable(payload) else payload
            t1 = time.perf_counter()
            try:
                resp = sc.request(doc)
            except (ConnectionError, OSError):
                on_response(None, None, time.perf_counter() - t1, doc)
                sc.close()
                sc = sd.SocketClient(port)
                continue
            status = resp.get("status")
            on_response(status, resp.get("error"),
                        time.perf_counter() - t1, doc)
            if status == 200:
                delay = backoff_s
            elif backoff_s:
                time.sleep(delay)
                delay = min(delay * 2.0, 64.0 * backoff_s)
    finally:
        sc.close()


def _scan_violations(tel_dir: str) -> dict:
    """Parse the phase's telemetry segments and count
    ``predicted_infeasible`` journeys that ever reached admission or a
    device — the knowingly-admitted-SLA-violation gate (must be 0)."""
    refused = 0
    violations = 0
    for path in sorted(_glob.glob(
            os.path.join(tel_dir, "keystone_telemetry_*.jsonl"))):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail of a live segment
                if rec.get("kind") != "journey":
                    continue
                j = rec.get("journey") or {}
                if j.get("outcome") != "predicted_infeasible":
                    continue
                refused += 1
                phases = {p.get("phase") for p in j.get("phases") or []}
                if phases & {"admitted", "dispatched", "delivered"}:
                    violations += 1
    return {"journeys_refused": refused, "violations": violations}


def run_capacity_bench(args) -> dict:
    import tempfile

    import serve_daemon as sd  # tools/ is on sys.path when run as a script

    from bench_serve import build_chain, lat_stats
    from keystone_tpu.utils.metrics import capacity_counters
    from keystone_tpu.utils.telemetry import reset_telemetry
    from keystone_tpu.workflow.daemon import ServingDaemon, Tenant
    from keystone_tpu.workflow.serialization import save_artifact

    d = args.d
    out_dir = tempfile.mkdtemp(prefix="keystone_capacity_bench_")
    chain = build_chain(d, args.features, args.classes, args.seed)
    pipe = chain.to_pipeline().fit()
    art = os.path.join(out_dir, "model.kart")
    save_artifact(pipe, art, feature_shape=(d,), dtype="float32")

    gold_x = np.zeros((GOLD_ROWS, d), dtype=np.float32).tolist()
    be_x = {
        BE_ROWS_A: np.zeros((BE_ROWS_A, d), dtype=np.float32).tolist(),
        BE_ROWS_B: np.zeros((BE_ROWS_B, d), dtype=np.float32).tolist(),
    }
    tenants = {
        "cap-gold": Tenant("gold", "cap-gold", qps=0, tier="gold"),
        "cap-be": Tenant("flood", "cap-be", qps=0, tier="best_effort"),
    }

    def make_daemon(tag, gold_deadline_ms, be_deadline_ms):
        return ServingDaemon(
            artifact=art, tenants=dict(tenants), devices=1,
            max_batch=MAX_BATCH, max_rows=MAX_ROWS, max_delay_ms=0.5,
            max_pending=args.max_pending, pending_budget=args.max_pending,
            gold_deadline_ms=gold_deadline_ms,
            be_deadline_ms=be_deadline_ms,
            name=f"capacity-bench-{tag}",
        )

    lock = threading.Lock()

    from keystone_tpu.config import config

    prior_env = {
        k: os.environ.get(k)
        for k in ("KEYSTONE_TELEMETRY_DIR", "KEYSTONE_CAPACITY_MODEL")
    }
    # The knobs are config snapshots (env read at import): mutate the
    # config object directly, the documented programmatic override.
    prior_cfg = (config.capacity_min_samples, config.capacity_replan_s)
    config.capacity_min_samples = args.min_samples
    config.capacity_replan_s = args.replan_s

    # ---- self-calibration: shallow vs full-depth best-effort p50
    # through the wire, model off, no deadline pressure. The geometric
    # midpoint becomes the flood's best-effort deadline: infeasible at
    # the flood's queue depth, comfortably feasible shallow.
    os.environ.pop("KEYSTONE_TELEMETRY_DIR", None)
    os.environ["KEYSTONE_CAPACITY_MODEL"] = "0"
    reset_telemetry()
    cal = make_daemon("cal", 60000.0, 60000.0)

    def measure(n_clients, seconds):
        lats: list = []

        def on_resp(status, _err, dt, _doc):
            if status == 200:
                with lock:
                    lats.append(dt)

        stop_t = time.perf_counter() + seconds
        ts = [
            threading.Thread(
                target=_closed_loop,
                args=(cal.socket_port, sd,
                      {"x": be_x[BE_ROWS_A], "key": "cap-be"},
                      stop_t, on_resp),
            )
            for _ in range(n_clients)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return lats

    try:
        shallow = measure(2, args.calibrate_seconds)
        loaded = measure(args.be_clients, args.calibrate_seconds)
    finally:
        cal.close()
    if not shallow or not loaded:
        raise RuntimeError("calibration served no traffic")
    base_p50 = lat_stats(shallow)["p50_ms"]
    loaded_p50 = lat_stats(loaded)["p50_ms"]
    # Weighted geometric mean, biased toward the shallow floor: the
    # flood class must be infeasible at any meaningful depth (so the
    # A/B contrast doesn't depend on which queue-depth equilibrium the
    # learned drain rate settles into) yet comfortably feasible at an
    # empty queue (so refusing it all would trip the accuracy guard).
    be_deadline_ms = max(4.0, base_p50 ** 0.7
                         * max(loaded_p50, base_p50) ** 0.3)
    # The loose rider class clears the full-depth wait with headroom.
    loose_deadline_ms = max(35.0, 4.0 * loaded_p50)
    gold_deadline_ms = max(1000.0, 50.0 * loaded_p50)

    # ---- one flood phase: identical traffic, model off vs on --------------
    def run_phase(tag: str, model_on: bool) -> dict:
        tel_dir = os.path.join(out_dir, f"tel_{tag}")
        os.makedirs(tel_dir, exist_ok=True)
        os.environ["KEYSTONE_TELEMETRY_DIR"] = tel_dir
        os.environ["KEYSTONE_CAPACITY_MODEL"] = "1" if model_on else "0"
        reset_telemetry()
        cap0 = capacity_counters.snapshot()
        daemon = make_daemon(tag, gold_deadline_ms, be_deadline_ms)
        outcomes = {"ok_gold": 0, "ok_be": 0, "late_200": 0,
                    "predicted_refused": 0, "rejected": 0, "expired": 0,
                    "closed": 0, "error": 0, "conn": 0}
        gold_lats: list = []
        try:
            def warm_resp(*_a):
                return None

            # Warmup: shallow mixed traffic — compiles every bucket and
            # (model-on) feeds the model past KEYSTONE_CAPACITY_MIN_SAMPLES
            # before the measured window. Identical in both phases.
            warm_t = time.perf_counter() + args.warmup_seconds
            warm = [
                threading.Thread(
                    target=_closed_loop,
                    args=(daemon.socket_port, sd,
                          {"x": gold_x, "key": "cap-gold"},
                          warm_t, warm_resp),
                ),
            ] + [
                threading.Thread(
                    target=_closed_loop,
                    args=(daemon.socket_port, sd,
                          {"x": be_x[BE_ROWS_A], "key": "cap-be"},
                          warm_t, warm_resp),
                )
                for _ in range(2)
            ]
            for t in warm:
                t.start()
            for t in warm:
                t.join()

            # Flood: gold closed-loop probes + best-effort overload;
            # best-effort rows shift 1 -> 2 at the halfway mark (the
            # traffic-mix shift the re-plan loop must notice).
            t_start = time.perf_counter()
            t_half = t_start + args.flood_seconds / 2.0
            stop_t = t_start + args.flood_seconds

            def gold_resp(status, err, dt, doc):
                if status == 200:
                    with lock:
                        if dt * 1e3 <= gold_deadline_ms:
                            outcomes["ok_gold"] += 1
                        else:
                            outcomes["late_200"] += 1
                        gold_lats.append(dt)
                    return
                be_resp(status, err, dt, doc)  # same failure taxonomy

            def be_resp(status, err, dt, doc):
                with lock:
                    if status == 200:
                        # Goodput counts DEADLINE-MET responses only: a
                        # late 200 (dispatched before expiry, delivered
                        # after the deadline) is a served SLA violation,
                        # not goodput.
                        ddl = doc.get("deadline_ms") or be_deadline_ms
                        if dt * 1e3 <= ddl:
                            outcomes["ok_be"] += 1
                        else:
                            outcomes["late_200"] += 1
                    elif status == 429 and err == "predicted_infeasible":
                        outcomes["predicted_refused"] += 1
                    elif status == 429:
                        outcomes["rejected"] += 1
                    elif status == 504:
                        outcomes["expired"] += 1
                    elif status == 503:
                        outcomes["closed"] += 1
                    elif status is None:
                        outcomes["conn"] += 1
                    else:
                        outcomes["error"] += 1

            def be_payload():
                rows = (BE_ROWS_A if time.perf_counter() < t_half
                        else BE_ROWS_B)
                return {"x": be_x[rows], "key": "cap-be"}

            # A small DEDICATED pool of loose-deadline 1-row riders:
            # admissible under load (their deadline survives a full
            # queue) and the micro-batcher's eligible cargo. Closed-loop,
            # so at most --rider-clients of them ever occupy the queue —
            # they must not become queue mass the gold tier waits behind.
            rider_payload = {"x": be_x[BE_ROWS_A], "key": "cap-be",
                             "deadline_ms": loose_deadline_ms}

            floods = [
                threading.Thread(
                    target=_closed_loop,
                    args=(daemon.socket_port, sd, be_payload, stop_t,
                          be_resp),
                    kwargs={"backoff_s": args.backoff_ms / 1e3},
                )
                for _ in range(args.be_clients)
            ] + [
                threading.Thread(
                    target=_closed_loop,
                    args=(daemon.socket_port, sd, rider_payload, stop_t,
                          be_resp),
                    kwargs={"backoff_s": args.backoff_ms / 1e3},
                )
                for _ in range(args.rider_clients)
            ] + [
                threading.Thread(
                    target=_closed_loop,
                    args=(daemon.socket_port, sd,
                          {"x": gold_x, "key": "cap-gold"},
                          stop_t, gold_resp),
                    kwargs={"backoff_s": args.backoff_ms / 1e3},
                )
                for _ in range(args.gold_clients)
            ]
            for t in floods:
                t.start()
            for t in floods:
                t.join()
            wall = time.perf_counter() - t_start
            stats = daemon.stats()
        finally:
            daemon.close()

        cap1 = capacity_counters.snapshot()
        delta = {
            k: cap1.get(k, 0) - cap0.get(k, 0)
            for k in set(cap0) | set(cap1)
        }
        goodput = (outcomes["ok_gold"] + outcomes["ok_be"]) / max(wall, 1e-9)
        phase = {
            "model_on": model_on,
            "goodput_per_s": round(goodput, 1),
            "served": outcomes["ok_gold"] + outcomes["ok_be"],
            "outcomes": outcomes,
            "gold": lat_stats(gold_lats) if gold_lats else None,
            "capacity_counters": {k: v for k, v in delta.items() if v},
            "capacity_stats": stats["capacity"],
            "wall_s": round(wall, 3),
        }
        if model_on:
            phase["telemetry_scan"] = _scan_violations(tel_dir)
        return phase

    try:
        off = run_phase("off", model_on=False)
        on = run_phase("on", model_on=True)
    finally:
        for k, v in prior_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        config.capacity_min_samples, config.capacity_replan_s = prior_cfg
        reset_telemetry()

    scan = on["telemetry_scan"]
    counters = on["capacity_counters"]
    gold_off = (off["gold"] or {}).get("p99_ms")
    gold_on = (on["gold"] or {}).get("p99_ms")
    replans = counters.get("replans", 0) + counters.get(
        "replans_suppressed", 0)
    result = {
        "metric": "serve_capacity",
        "unit": "req/s",
        "be_clients": args.be_clients,
        "gold_clients": args.gold_clients,
        "flood_seconds": args.flood_seconds,
        "calibration": {
            "shallow_p50_ms": round(base_p50, 3),
            "loaded_p50_ms": round(loaded_p50, 3),
            "be_deadline_ms": round(be_deadline_ms, 3),
            "loose_deadline_ms": round(loose_deadline_ms, 1),
            "gold_deadline_ms": round(gold_deadline_ms, 1),
        },
        "off": off,
        "on": on,
        "goodput_off_per_s": off["goodput_per_s"],
        "goodput_on_per_s": on["goodput_per_s"],
        "gold_p99_off_ms": gold_off,
        "gold_p99_on_ms": gold_on,
        "predicted_refusals": counters.get("predicted_refusals", 0),
        "microbatches_formed": counters.get("microbatches_formed", 0),
        "microbatch_rows_filled": counters.get("microbatch_rows_filled", 0),
        "replans": counters.get("replans", 0),
        "replans_suppressed": counters.get("replans_suppressed", 0),
        "guard_checked": on["capacity_stats"].get("guard_checked", 0),
        "guard_violations": counters.get("guard_violations", 0),
        "knowing_violations": scan["violations"],
        "late_200_off": off["outcomes"]["late_200"],
        "late_200_on": on["outcomes"]["late_200"],
        "pass": {
            "goodput_improved": (
                on["goodput_per_s"] > off["goodput_per_s"]
            ),
            "gold_p99_ok": bool(
                gold_off is not None and gold_on is not None
                and gold_on <= gold_off * args.gold_p99_tolerance
            ),
            "zero_knowing_violations": scan["violations"] == 0,
            "refusals_engaged": counters.get("predicted_refusals", 0) > 0,
            "refusals_on_wire": (
                on["outcomes"]["predicted_refused"] > 0
            ),
            "microbatches_formed": (
                counters.get("microbatches_formed", 0) > 0
            ),
            "model_reacted": replans > 0,
            "off_phase_untouched": (
                off["capacity_stats"] == {"enabled": False}
                and not off["capacity_counters"].get("predicted_refusals")
                and not off["capacity_counters"].get("microbatches_formed")
            ),
            "zero_unresolved": (
                off["outcomes"]["conn"] + on["outcomes"]["conn"] == 0
                and off["outcomes"]["error"] + on["outcomes"]["error"] == 0
            ),
        },
    }
    result["ok"] = all(result["pass"].values())
    return result


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--d", type=int, default=128, help="input feature dim")
    ap.add_argument("--features", type=int, default=2048,
                    help="random-feature width of the serving head")
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--be-clients", type=int, default=24,
                    help="closed-loop best-effort flood clients — the "
                    "overload depth the deadline is calibrated against")
    ap.add_argument("--gold-clients", type=int, default=2)
    ap.add_argument("--rider-clients", type=int, default=3,
                    help="dedicated loose-deadline 1-row best-effort "
                    "clients — the micro-batcher's eligible cargo, "
                    "closed-loop so they never become deep queue mass")
    ap.add_argument("--calibrate-seconds", type=float, default=1.0)
    ap.add_argument("--warmup-seconds", type=float, default=1.2,
                    help="shallow mixed traffic before each measured "
                    "flood: compiles every bucket and warms the model "
                    "past --min-samples")
    ap.add_argument("--flood-seconds", type=float, default=8.0)
    ap.add_argument("--backoff-ms", type=float, default=8.0,
                    help="client retry backoff after any non-200 — "
                    "identical in both phases")
    ap.add_argument("--max-pending", type=int, default=256,
                    help="service queue + admission budget, sized so "
                    "queue-full/budget 429s never mask the A/B: the "
                    "only refuser under flood is the model")
    ap.add_argument("--min-samples", type=int, default=48,
                    help="KEYSTONE_CAPACITY_MIN_SAMPLES for the phases "
                    "(warmup feeds well past this)")
    ap.add_argument("--replan-s", type=float, default=0.25,
                    help="KEYSTONE_CAPACITY_REPLAN_S for the phases")
    ap.add_argument("--gold-p99-tolerance", type=float, default=1.15,
                    help="model-on gold p99 must stay within this "
                    "factor of model-off (equal-or-better + timer "
                    "noise)")
    ap.add_argument("--out", type=str, default=None,
                    help="append/replace the serve_capacity row in this "
                    "BENCH_serve.json")
    args = ap.parse_args()

    from keystone_tpu.utils.platform import ensure_live_backend

    backend = ensure_live_backend()

    from bench_serve import write_result
    from keystone_tpu.config import config
    from keystone_tpu.utils.metrics import environment_fingerprint

    # Bench isolation (the bench_serve precedent): an ambient ladder /
    # precision / plan pin would change what the phases measure.
    os.environ.pop("KEYSTONE_SERVE_BUCKETS", None)
    os.environ.pop("KEYSTONE_SERVE_PRECISION", None)
    config.serve_buckets = ()
    config.serve_precision = "f32"
    config.plan_resources = True

    result = run_capacity_bench(args)
    result["backend"] = backend
    result["host_cores"] = os.cpu_count()
    result["env"] = environment_fingerprint()
    line = json.dumps(result)
    print(line)
    if args.out:
        write_result(args.out, line, result["metric"])
    sys.exit(0 if result["ok"] else 1)


if __name__ == "__main__":
    main()
