"""Host-side descriptor throughput: native dense SIFT + LCS img/s per core.

The north-star projection (tools/northstar.py) shows the ImageNet
pipeline is HOST-bound on a v5e-64: the chips finish the FV encode and
the 64k-dim solve in seconds, so the budget hinges on how fast the host
fleet can decode + extract SIFT/LCS descriptors. Decode was measured in
NOTES_r3 §7 (273 img/s/core native at 512->256px); this tool measures
the missing piece — the clean-room C++ descriptor kernels
(native/src/sift.cpp, OpenMP) and the LCS extractor at the reference's
256px / step-4 configuration — so the projection's REQUIREMENT row can
be stated in cores, not hopes.

Usage: python tools/bench_host_featurize.py [--images 64] [--size 256]
Prints one JSON line. Pure host work: safe to run while the chip is dead.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(images: int, size: int, step: int) -> dict:
    from keystone_tpu.native import available
    from keystone_tpu.nodes.images.external.sift import SIFTExtractor
    from keystone_tpu.nodes.images.lcs import LCSExtractor

    rng = np.random.default_rng(0)
    gray = rng.uniform(size=(images, size, size)).astype(np.float32)
    rgb = rng.uniform(size=(images, size, size, 3)).astype(np.float32)

    out = {"images": images, "size": size, "step": step,
           "native_available": bool(available()),
           "host_cores": os.cpu_count()}
    if not available():
        return out

    sift = SIFTExtractor(step=step)
    lcs = LCSExtractor(step=step)

    for name, fn, data in (("sift", sift.apply_batch, gray),
                           ("lcs", lcs.apply_batch, rgb)):
        # Warm up at the FULL batch shape (first jnp trace compiles per
        # shape) and time through the host materialization — the LCS path
        # dispatches asynchronously, so the fetch IS part of the work.
        np.asarray(fn(data))
        t0 = time.perf_counter()
        d = np.asarray(fn(data))
        dt = max(time.perf_counter() - t0, 1e-9)
        out[f"{name}_img_per_sec"] = round(images / dt, 1)
        out[f"{name}_desc_per_img"] = int(d.shape[1]) if d.ndim >= 2 else None
        out[f"{name}_desc_dim"] = int(d.shape[-1])
    if out["sift_img_per_sec"] > 0 and out["lcs_img_per_sec"] > 0:
        both = 1.0 / (
            1.0 / out["sift_img_per_sec"] + 1.0 / out["lcs_img_per_sec"]
        )
        out["both_branches_img_per_sec"] = round(both, 1)
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=64)
    ap.add_argument("--size", type=int, default=256)
    ap.add_argument("--step", type=int, default=4)
    args = ap.parse_args()
    # HOST rates are the quantity under test: pin jax (the LCS extractor is
    # a jnp program) to CPU before any backend init — on the ambient TPU
    # platform this tool would otherwise measure the chip, or hang for
    # minutes when the relay is dead.
    # ONE OpenMP thread: the published rates are img/s PER CORE (that is
    # how northstar.py consumes them); the native SIFT kernel is OpenMP-
    # parallel and would otherwise report a per-process rate inflated by
    # nproc on multi-core hosts.
    os.environ["OMP_NUM_THREADS"] = "1"
    from keystone_tpu.utils.platform import force_cpu

    force_cpu()
    out = measure(args.images, args.size, args.step)
    out["omp_threads"] = 1
    print(json.dumps({"metric": "host_descriptor_img_per_sec", **out}))


if __name__ == "__main__":
    main()
