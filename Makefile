# Developer entry points. Pipelines launch via bin/run-pipeline.sh.

.PHONY: test t1 chaos chaos-elastic native bench bench-serve bench-serve-overload bench-serve-replicas bench-serve-daemon bench-serve-precision bench-capacity bench-fit bench-opt bench-multichip bench-imagenet bench-online trace-demo trace-report obs-serve serve-daemon profile-demo bench-watch lint dryrun clean tpu-checkride sentinel northstar acceptance

# The canonical tier-1 verify (ROADMAP.md), verbatim at the defaults —
# builders and CI invoke this one entry point instead of hand-copying the
# command; `chaos` reuses it with T1_ENV/T1_LOG overridden so the two can
# never drift. bash for pipefail/PIPESTATUS.
T1_LOG ?= /tmp/_t1.log
T1_ENV ?=
t1: SHELL := /bin/bash
t1:
	set -o pipefail; rm -f $(T1_LOG); timeout -k 10 870 env JAX_PLATFORMS=cpu $(T1_ENV) python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee $(T1_LOG); rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' $(T1_LOG) | tr -cd . | wc -c); exit $$rc

# Tier-1 under the standard fault plan (utils/reliability.py): transient
# IOErrors at 5% of record boundaries, one injected device OOM, and 5% of
# daemon client connections dropping before the response write — seeded
# and deterministic. The suite must pass UNCHANGED: every injected fault
# is recovered (retry/backoff, quarantine, chunk downshift) invisibly,
# and a dropped connection's request still resolves (journey outcome
# conn_drop, zero unresolved futures; clients simply retry).
chaos:
	$(MAKE) t1 T1_ENV="KEYSTONE_FAULTS=io:0.05,oom:1,conn_drop:0.05 KEYSTONE_FAULTS_SEED=0" T1_LOG=/tmp/_chaos.log
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	  KEYSTONE_FAULTS=io:0.05,oom:1 KEYSTONE_FAULTS_SEED=0 \
	  python -m keystone_tpu.pipelines.images.imagenet_sift_lcs_fv \
	  --stream --fv-backend pallas --gmm-k 2 --pca-dims 4 --top-k 2 \
	  --synthetic-n 96 --synthetic-classes 4 --stream-batch 32 \
	  --fit-sample-images 64 --checkpoint-dir /tmp/_chaos_imagenet_ckpt
	$(MAKE) chaos-elastic

# Elastic-mesh chaos leg (tools/chaos_elastic.py): fits killed mid-solve
# at width 8 resume at widths 4 AND 16 under the same fault plan, and
# the migrated resume must match the uninterrupted target-width fit
# BIT-FOR-BIT (stream solve, BCD epochs, OnlineState in all three
# forgetting modes) — with every migration counted and zero silent ones.
chaos-elastic:
	JAX_PLATFORMS=cpu KEYSTONE_FAULTS=io:0.05,oom:1 KEYSTONE_FAULTS_SEED=0 \
	  python tools/chaos_elastic.py --quick

# One-command resumable live-chip evidence harness: probes the TPU, runs
# bench f32/bf16 + MFU sweep + Pallas Mosaic compile + streamed-overlap +
# memory stats + entry() compile, checkpointing each step to .checkride/
# and aggregating TPU_REPORT.json. Safe to re-run: TPU-complete steps skip,
# CPU-fallback steps retry when the chip is back.
tpu-checkride:
	python tools/checkride.py

# Probe loop that relaunches the resumable checkride whenever the chip
# returns; exits once TPU_REPORT.json is complete_on_tpu.
sentinel:
	python tools/checkride_sentinel.py

# ImageNet v5e-64 bottleneck projection from measured rates (TPU_REPORT +
# HOSTBENCH); stages without silicon evidence are labelled, not claimed.
northstar:
	python tools/northstar.py

# Quality floors, all eight canonical pipelines, one pass/fail table.
acceptance:
	python tools/acceptance.py --synthetic

test:
	python -m pytest tests/ -q

native:
	$(MAKE) -C keystone_tpu/native

bench:
	python bench.py

# Shape-stable serving: per-shape jit vs bucketed+AOT-warmed on a
# mixed-size request trace. Gate: zero post-warmup compiles, >=2x p99.
# Writes the machine-readable BENCH_serve.json regression anchor.
bench-serve:
	python tools/bench_serve.py --out BENCH_serve.json

# Serving under 2x sustained over-capacity against the bounded queue +
# deadlines: reports fast-fail rate and accepted p99 — degradation must
# be bounded (rejections, not a latency cliff) and no future stranded.
bench-serve-overload:
	python tools/bench_serve.py --overload

# Replica-pool scaling on the forced 8-host-device CPU mesh: the same
# uniform trace served at devices=1 vs devices=4 through the pipelined
# dispatcher. Gates: outputs bit-identical to single-device, every
# replica serves traffic (dispatch balance max/min <= 3x); the >=1.3x
# throughput gate is hard only on >=2-core hosts (fingerprinted in the
# appended BENCH_serve.json row).
bench-serve-replicas:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	  python tools/bench_serve.py --devices 4 --out BENCH_serve.json

# Networked serving daemon smoke: export two demo artifacts, stand up a
# live daemon (HTTP/JSON + framed-socket ingress, tenant admission),
# drive both wires, verify 403/429 admission, /healthz generation
# identity, and a hot-swap UNDER TRAFFIC with zero dropped requests and
# per-generation bit-identity. Tier-1 runs the same smoke in-process
# (tests/test_daemon.py).
serve-daemon:
	JAX_PLATFORMS=cpu python tools/serve_daemon.py --smoke

# Daemon overload + swap-under-load bench through the REAL socket: flood
# at 2x the admitted best-effort concurrency — the excess must fast-fail
# 429 at admission (zero device cost) while the gold tenant's p99 stays
# within 2x its deadline across TWO mid-flood hot-swaps. APPENDS the
# fingerprinted serve_daemon row to the BENCH_serve.json history that
# `make bench-watch` regresses against.
bench-serve-daemon:
	JAX_PLATFORMS=cpu python tools/bench_serve.py --daemon --out BENCH_serve.json

# Capacity-loop A/B: the same shifting-mix flood with the learned
# capacity model off (the pre-model baseline) vs on. Hard gates: model-on
# goodput (deadline-met 200s/s) beats model-off at equal-or-better gold
# p99, zero predicted-infeasible journeys ever reached a device, at
# least one cross-tenant micro-batch formed, and the re-plan loop
# reacted to the mid-flood mix shift. APPENDS the fingerprinted
# serve_capacity row to the BENCH_serve.json history `make bench-watch`
# regresses against.
bench-capacity:
	JAX_PLATFORMS=cpu python tools/bench_capacity.py --out BENCH_serve.json

# Memory-bounded precision A/B: f32 hand-picked single-bucket ladder vs
# HBM-planned ladder + bf16 through the same trained canonical head.
# Hard gates on any backend: wall AND p99 beat the baseline, planned f32
# bit-identical to hand-picked f32, quality within the declared
# tolerance of the f32 oracle (qualify() refuses otherwise), zero
# post-warmup compiles. APPENDS the fingerprinted serve_precision row to
# the BENCH_serve.json history `make bench-watch` regresses against.
bench-serve-precision:
	JAX_PLATFORMS=cpu python tools/bench_serve.py --precision --out BENCH_serve.json

# Observability smoke: a small fit + streamed solve + serve under
# KEYSTONE_TRACE=1, Chrome-trace exported to /tmp/keystone_trace.json,
# schema-validated, and checked for full span coverage (executor nodes,
# solver chunks, prefetch residency, serving lifecycle). Tier-1 runs the
# same demo in-process via tests/test_observability.py.
trace-demo:
	KEYSTONE_TRACE=1 JAX_PLATFORMS=cpu python tools/trace_demo.py --out /tmp/keystone_trace.json
	JAX_PLATFORMS=cpu python tools/trace_report.py /tmp/keystone_trace.json --top 12

# Durable-telemetry smoke: run the live daemon smoke with journey export
# on (KEYSTONE_TELEMETRY_DIR), then — after the daemon has exited —
# reconstruct the full cross-process timeline and the per-tenant SLO
# report from the on-disk segments ALONE. Tier-1 runs the same
# reconstruction in-process (tests/test_trace_report.py).
trace-report:
	rm -rf /tmp/keystone_telemetry && mkdir -p /tmp/keystone_telemetry
	KEYSTONE_TELEMETRY_DIR=/tmp/keystone_telemetry KEYSTONE_TRACE=1 \
	  JAX_PLATFORMS=cpu python tools/serve_daemon.py --smoke
	JAX_PLATFORMS=cpu python tools/trace_report.py \
	  --telemetry /tmp/keystone_telemetry --out /tmp/keystone_journeys.json
	JAX_PLATFORMS=cpu python tools/trace_report.py \
	  --telemetry /tmp/keystone_telemetry --slo

# Observability export smoke: stand up a live warmed PipelineService +
# the stdlib metrics server, fetch /metrics and /healthz over a real
# socket, validate the Prometheus text exposition (shared
# validate_prometheus_text oracle), cross-check scraped counts against
# metrics_registry.snapshot(), and assert /healthz flips to 503 after
# close(). Tier-1 runs the same smoke in-process
# (tests/test_flight_recorder.py).
obs-serve:
	JAX_PLATFORMS=cpu python tools/metrics_server.py

# Training-side profiling smoke: a small fit + apply under the resource
# profiler — every executed node must get an attribution row with
# nonzero wall time, the solve node's cost-model FLOPs must land within
# 2x of the achieved_tflops oracle, KEYSTONE_PROFILE=0 outputs must be
# bit-identical to profiled ones, and a kill-mid-solve chaos run must
# auto-dump a flight-recorder journey naming the last completed chunk.
# Tier-1 runs the same demo in-process (tests/test_profile.py).
profile-demo:
	JAX_PLATFORMS=cpu python tools/profile_report.py --demo

# Stage-parallel executor walk: a two-branch host-featurize -> solve
# pipeline fitted under the legacy serial walk (KEYSTONE_EXEC_WORKERS=0)
# vs the ready-set scheduler (=4). Gates: predictions bit-identical,
# >=1.3x wall-clock speedup (hard only on >=2-core hosts — one core
# cannot overlap two host branches; there the gate is "no worse than
# 0.75x", the replica-bench precedent). APPENDS the fingerprinted row to
# the BENCH_fit.json history `make bench-watch` regresses against.
bench-fit:
	JAX_PLATFORMS=cpu python tools/bench_fit.py --out BENCH_fit.json

# Profile-guided optimizer A/B: the canonical re-used-subchain and
# two-branch pipelines fitted-and-applied optimizer-off vs optimizer-on,
# where "on" consumes the MEASURED profile a prior fit(profile=True)
# stored (zero sample-run executions, counted and gated). Gates:
# predictions bit-identical, >=1.2x wall-clock win per pipeline (hard on
# any core count — the win is recompute avoidance, not overlap), zero
# sample runs. APPENDS the fingerprinted row to the BENCH_fit.json
# history `make bench-watch` regresses against; prints the optimizer's
# decision table (tools/profile_report.py --decisions renders the same
# surface standalone).
bench-opt:
	JAX_PLATFORMS=cpu python tools/bench_optimizer.py --out BENCH_fit.json

# Mesh-native data-parallel fit bench: the canonical two-branch jittable
# featurize -> solve pipeline fitted in a 1-device and an N-fake-device
# subprocess (XLA_FLAGS=--xla_force_host_platform_device_count, the
# test_multihost precedent), each A/Bing the explicitly-specced sharded
# walk against the single-device walk. Gates: sharded predictions
# bit-identical to the single-device walk (hard, always, both widths),
# zero silent single-device fallbacks (registry-counter-verified; the
# bench's held-out batch is deliberately non-divisible so the mask-pad
# path is always exercised), rows/s scaling hard only on real multi-chip
# hardware (fake CPU devices time-slice the host — the PR-5/PR-9
# precedent). APPENDS the fingerprinted fit_multichip row to the
# BENCH_fit.json history `make bench-watch` regresses against.
bench-multichip:
	JAX_PLATFORMS=cpu python tools/bench_multichip.py --out BENCH_fit.json

# Real-pipeline multichip bench: the ImageNet SIFT+LCS+FV featurize ->
# BlockLS solve chain fitted in 1-device and N-fake-device subprocesses
# (bench-multichip precedent), with the fused jittable tail lowered
# through SpecLayout.jit under buffer donation. Hard gates: sharded
# predictions bit-identical to the single-device walk, donation
# invisible (donate-on preds digest == donate-off), Pallas FV active on
# the sharded path (counter-verified), zero silent fallbacks, and the
# donation decision path exercised (buffers_donated + donation_refused
# > 0 — the flagship's shrinking featurize stages legitimately refuse,
# see README "Fused & donated fits"). Rows/s scaling and the
# donated-vs-undonated peak-HBM gate are hard only on real multi-chip
# hardware (fake CPU devices time-slice the host and report no HBM).
# APPENDS the fingerprinted fit_imagenet_multichip row to BENCH_fit.json.
bench-imagenet:
	JAX_PLATFORMS=cpu python tools/bench_imagenet.py --out BENCH_fit.json

# Online-learning drift gate: a label-shifted synthetic stream folds
# into the retained gram/AtB accumulators with time-decay, re-solves,
# and hot-swaps the refreshed model into a LIVE daemon mid-traffic.
# Hard gates: post-refresh accuracy (measured through the wire on the
# new generation) recovers to within tolerance of a full refit over the
# shifted data, the online re-solve wall sits >=2x below the full-refit
# wall, and the swap-under-refresh leaves zero dropped requests /
# unresolved journeys. APPENDS the fingerprinted fit_online row to the
# BENCH_fit.json history `make bench-watch` regresses against. Tier-1
# runs the same harness in-process (tests/test_online.py).
bench-online:
	JAX_PLATFORMS=cpu python tools/bench_online.py --out BENCH_fit.json

# Bench regression sentinel: parse every BENCH_*/MULTICHIP_*/BENCH_serve/
# BENCH_fit history row, fit per-metric noise bands from
# fingerprint-compatible runs, exit nonzero naming any metric whose
# latest row regresses.
# Tier-1 runs the same gate in-process (tests/test_bench_watch.py).
bench-watch:
	python tools/bench_watch.py

# Static analysis, both layers, against the checked-in expectations:
# keystone_lint.py (stdlib-ast invariant checker: lock discipline,
# env-read-once, resolve-once, perf_counter timing, broad handlers,
# dispatch host syncs) is nonzero on any finding NOT in
# tools/lint_baseline.json; lint_report.py (graph layer) must lint the
# canonical serving chains clean AND refuse the row-coupled control
# chain. Tier-1 runs both in-process (tests/test_keystone_lint.py,
# tests/test_analysis.py) so this gate can never silently rot.
lint:
	python tools/keystone_lint.py
	JAX_PLATFORMS=cpu python tools/lint_report.py

dryrun:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	  python -c \
	  "import jax; jax.config.update('jax_platforms','cpu'); \
	   import __graft_entry__ as g; g.dryrun_multichip(8)"

clean:
	$(MAKE) -C keystone_tpu/native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
