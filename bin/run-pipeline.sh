#!/usr/bin/env bash
# Pipeline launcher — the `bin/run-pipeline.sh <PipelineClass> <args...>`
# entry point (Ref: bin/run-pipeline.sh wrapping spark-submit, BASELINE.json).
# Here it maps the reference's pipeline class names onto python modules.
#
# Env knobs (the KEYSTONE_MEM analog):
#   KEYSTONE_PLATFORM=cpu|axon     force the JAX platform (default: auto)
#   KEYSTONE_NUM_DEVICES=N         virtual CPU device count (testing meshes)
#   KEYSTONE_NO_FUSE=1             disable chain fusion (debugging)
#   KEYSTONE_AUTO_CACHE=1          profile + auto-insert cache nodes
#   KEYSTONE_CACHE_DIR=path        fitted-prefix store; a rerun with the same
#                                  data + hyperparams skips refits entirely
#                                  (default: .keystone_cache next to the repo;
#                                  set empty to disable)
set -euo pipefail

if [[ $# -lt 1 ]]; then
  echo "usage: $0 <Pipeline> [args...]" >&2
  echo "pipelines: MnistRandomFFT LinearPixels RandomPatchCifar" >&2
  echo "           NewsgroupsPipeline AmazonReviewsPipeline TimitPipeline" >&2
  echo "           VOCSIFTFisher ImageNetSiftLcsFV" >&2
  exit 64
fi

PIPELINE="$1"; shift

case "$PIPELINE" in
  MnistRandomFFT)        MOD=keystone_tpu.pipelines.images.mnist_random_fft ;;
  LinearPixels)          MOD=keystone_tpu.pipelines.images.linear_pixels ;;
  RandomPatchCifar)      MOD=keystone_tpu.pipelines.images.random_patch_cifar ;;
  NewsgroupsPipeline)    MOD=keystone_tpu.pipelines.text.newsgroups ;;
  AmazonReviewsPipeline) MOD=keystone_tpu.pipelines.text.amazon_reviews ;;
  TimitPipeline)         MOD=keystone_tpu.pipelines.speech.timit ;;
  VOCSIFTFisher)         MOD=keystone_tpu.pipelines.images.voc_sift_fisher ;;
  ImageNetSiftLcsFV)     MOD=keystone_tpu.pipelines.images.imagenet_sift_lcs_fv ;;
  *) echo "unknown pipeline: $PIPELINE" >&2; exit 64 ;;
esac

REPO_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="${REPO_DIR}${PYTHONPATH:+:$PYTHONPATH}"
export KEYSTONE_CACHE_DIR="${KEYSTONE_CACHE_DIR-${REPO_DIR}/.keystone_cache}"

if [[ ! -f "${REPO_DIR}/${MOD//.//}.py" ]]; then
  echo "pipeline $PIPELINE is not implemented yet (module $MOD missing)" >&2
  exit 69
fi

if [[ -n "${KEYSTONE_NUM_DEVICES:-}" ]]; then
  export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=${KEYSTONE_NUM_DEVICES}"
fi
if [[ -n "${KEYSTONE_PLATFORM:-}" ]]; then
  export KEYSTONE_PLATFORM
fi

exec python -m "$MOD" "$@"
